"""Network registry (ZK-role discovery), distributed lock, REST control,
and the t-SNE render page.

Reference surfaces covered: ZooKeeperConfigurationRegister/Retriever
(discovery), HdfsLock (coordination lock),
StateTrackerDropWizardResource (GET status + POST control),
RenderApplication + assets (browsable scatter)."""

import json
import threading
import time
import urllib.request

import numpy as np


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())


def test_network_registry_master_discovery_and_ephemeral_workers():
    from deeplearning4j_tpu.parallel.registry import (
        NetworkRegistry, RegistryServer,
    )

    server = RegistryServer()
    addr = server.start()
    try:
        master = NetworkRegistry(addr, "job1", worker_ttl=0.5)
        worker = NetworkRegistry(addr, "job1", worker_ttl=0.5)

        # worker polls before the master registers -> must block then see it
        got = {}

        def retrieve():
            got["cfg"] = worker.retrieve_master(timeout=10.0)

        t = threading.Thread(target=retrieve)
        t.start()
        time.sleep(0.3)
        master.register_master({"coordinator": "10.0.0.1:1234"})
        t.join(timeout=10)
        assert got["cfg"] == {"coordinator": "10.0.0.1:1234"}

        # ephemeral workers: visible while heartbeating, gone after TTL
        worker.register_worker("w0", {"devices": 4})
        worker.register_worker("w1")
        assert master.list_workers() == ["w0", "w1"]
        time.sleep(0.8)  # > ttl, no re-registration
        assert master.list_workers() == []

        # jobs are namespaced
        other = NetworkRegistry(addr, "job2")
        other.register_worker("x")
        assert master.list_workers() == []
        assert other.list_workers() == ["x"]
    finally:
        server.stop()


def test_registry_lock_mutual_exclusion_and_lease_expiry():
    from deeplearning4j_tpu.parallel.registry import (
        NetworkRegistry, RegistryServer,
    )

    server = RegistryServer()
    addr = server.start()
    try:
        a = NetworkRegistry(addr, "job").lock("ckpt", owner="a", lease=30.0)
        b = NetworkRegistry(addr, "job").lock("ckpt", owner="b", lease=30.0)
        assert a.acquire(timeout=1.0)
        assert not b.acquire(timeout=0.4)  # held
        a.release()
        assert b.acquire(timeout=1.0)  # free again
        b.release()

        # a crashed holder's lease expires on its own (HdfsLock could not
        # do this — VERDICT r1 missing #6)
        crash = NetworkRegistry(addr, "job").lock("ckpt", owner="crash",
                                                  lease=0.4)
        assert crash.acquire(timeout=1.0)
        assert b.acquire(timeout=5.0)  # waits out the dead lease
        b.release()

        # context-manager form
        with NetworkRegistry(addr, "job").lock("other", owner="cm") as lk:
            assert lk.owner == "cm"

        # an EXPIRED holder must not destroy or steal the new holder's
        # lock (owner-checked release/renew)
        from deeplearning4j_tpu.parallel.registry import LeaseLostError

        import pytest as _pytest

        stale = NetworkRegistry(addr, "job").lock("own", owner="stale",
                                                  lease=0.3)
        assert stale.acquire(timeout=1.0)
        time.sleep(0.5)  # lease expires
        fresh = NetworkRegistry(addr, "job").lock("own", owner="fresh",
                                                  lease=30.0)
        assert fresh.acquire(timeout=2.0)
        stale.release()  # no-op: compare-and-delete fails silently
        with _pytest.raises(LeaseLostError):
            stale.renew()
        # fresh still holds it
        third = NetworkRegistry(addr, "job").lock("own", owner="third",
                                                  lease=30.0)
        assert not third.acquire(timeout=0.4)
        fresh.renew()  # holder renews fine
        fresh.release()

        # a raw if_owner renew that omits "value" must PRESERVE the held
        # value, not overwrite the owner with null (which would 409 the
        # real holder's every later renew/release) — ADVICE r2
        import json as _json
        import urllib.request as _rq

        holder = NetworkRegistry(addr, "job").lock(
            "raw", owner="h", lease=30.0
        )
        assert holder.acquire(timeout=1.0)
        req = _rq.Request(
            f"http://{addr}/kv/job/lock/raw",
            data=_json.dumps({"if_owner": "h", "ttl": 30.0}).encode(),
            method="PUT",
            headers={"Content-Type": "application/json"},
        )
        _rq.urlopen(req, timeout=5).read()
        holder.renew()  # would raise LeaseLostError before the fix
        holder.release()
    finally:
        server.stop()


def test_statetracker_rest_auth_token():
    """Control POSTs require X-Auth-Token when a token is configured
    (ADVICE r3: non-loopback binds expose mutation endpoints)."""
    import urllib.error

    from deeplearning4j_tpu.parallel.cluster import ClusterService

    svc = ClusterService()
    svc.minibatch = 32
    port = svc.start_rest_api(0, auth_token="sekrit")
    base = f"http://127.0.0.1:{port}/statetracker"
    try:
        # GET stays open (read-only status)
        assert _get(f"{base}/minibatch") == 32
        # POST without token -> 401, state unchanged
        try:
            _post(f"{base}/minibatch", {"value": 64})
            raise AssertionError("expected 401")
        except urllib.error.HTTPError as e:
            assert e.code == 401
        assert svc.minibatch == 32
        # POST with token succeeds
        req = urllib.request.Request(
            f"{base}/minibatch", data=json.dumps({"value": 64}).encode(),
            method="POST",
            headers={"Content-Type": "application/json",
                     "X-Auth-Token": "sekrit"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            assert json.loads(r.read()) == {"minibatch": 64}
        assert svc.minibatch == 64
    finally:
        svc.stop_rest_api()


def test_statetracker_generated_token_not_logged(caplog, tmp_path):
    """A generated control token must never appear in the log stream
    (CWE-532, ADVICE r4): only an 8-char fingerprint is logged; the full
    secret goes to a mode-0600 file."""
    import logging
    import os
    import stat

    from deeplearning4j_tpu.parallel.cluster import ClusterService

    svc = ClusterService()
    with caplog.at_level(logging.WARNING,
                         logger="deeplearning4j_tpu.parallel.cluster"):
        port = svc.start_rest_api(0, host="0.0.0.0")
    try:
        token = svc.auth_token
        assert token is not None and len(token) == 32
        log_text = caplog.text
        assert token not in log_text, "full secret leaked to the log"
        assert token[:8] in log_text  # fingerprint for correlation
        path = svc.auth_token_file
        assert os.path.exists(path)
        mode = stat.S_IMODE(os.stat(path).st_mode)
        assert mode == 0o600
        with open(path) as f:
            assert f.read() == token
    finally:
        svc.stop_rest_api()
    # stop cleans up the secret file (no stale token left in /tmp)
    assert svc.auth_token_file is None and not os.path.exists(path)


def test_statetracker_rest_post_control():
    from deeplearning4j_tpu.parallel.cluster import ClusterService

    svc = ClusterService()
    svc.model_description = "transformer d_model=16"
    svc.minibatch = 32
    port = svc.start_rest_api(0)
    base = f"http://127.0.0.1:{port}/statetracker"
    try:
        # GET parity (round-1 surface)
        assert _get(f"{base}/minibatch") == 32
        assert _get(f"{base}/phase") == "init"
        assert _get(base)["numbatchessofar"] == 0
        # printmodel ≙ StateTrackerDropWizardResource.printModel
        assert "transformer" in _get(f"{base}/printmodel")["model"]

        # POST minibatch changes live trainer state
        assert _post(f"{base}/minibatch", {"value": 64}) == {"minibatch": 64}
        assert svc.minibatch == 64
        # POST phase
        _post(f"{base}/phase", {"value": "finetune"})
        assert svc.phase == "finetune"
        # POST earlystop flips the blackboard; the trainer's
        # report_loss() check picks it up on its next cadence
        assert not svc.report_loss(1.0)
        _post(f"{base}/earlystop", {})
        assert svc.report_loss(0.5) is True

        # heartbeat over REST registers the worker; malformed meta is a
        # clean 400, not a handler crash
        _post(f"{base}/heartbeat", {"worker": "w9", "meta": {"step": 3}})
        assert svc.workers() == ["w9"]
        import urllib.error

        for bad in ({"worker": "w9", "meta": [1, 2]},
                    {"meta": {"step": 1}}):
            try:
                _post(f"{base}/heartbeat", bad)
                assert False, "expected HTTP 400"
            except urllib.error.HTTPError as e:
                assert e.code == 400
        # colliding key is dropped, not a TypeError
        _post(f"{base}/heartbeat",
              {"worker": "w9", "meta": {"worker_id": "evil", "step": 4}})
        assert svc.workers() == ["w9"]
    finally:
        svc.stop_rest_api()


def test_serve_tsne_browser_page_and_coords():
    from deeplearning4j_tpu.plot.plotter import serve_tsne

    words = ["alpha", "beta", "gamma"]
    coords = np.asarray([[0.0, 1.0], [2.0, 3.0], [-1.0, -2.0]])
    port = serve_tsne(words, coords)
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/", timeout=10
    ) as r:
        page = r.read().decode()
        assert r.headers["Content-Type"].startswith("text/html")
    # self-contained render page: canvas + the fetch of /coords
    assert "<canvas" in page and "/coords" in page and "<script>" in page
    data = _get(f"http://127.0.0.1:{port}/coords")
    assert data == [
        {"word": "alpha", "x": 0.0, "y": 1.0},
        {"word": "beta", "x": 2.0, "y": 3.0},
        {"word": "gamma", "x": -1.0, "y": -2.0},
    ]
