"""Multi-tenant serving: DRR fairness, quotas, batched LoRA, streaming.

The load-bearing claims, in order of appearance:

- **Fairness** — with a ``TenantRegistry`` attached, one tenant
  flooding the queue cannot starve its classmates: deficit-round-robin
  inside the priority class interleaves the victims' requests into the
  flood, measurably earlier than FIFO would, while the token streams
  stay byte-identical (the scheduler only reorders).
- **Quota** — a tenant's token bucket rejects at submit with
  ``QuotaExceeded`` (the 429 path), refills on the injected clock, and
  never affects other tenants' admission.
- **Batched LoRA** — the tentpole parity bar: a mixed-adapter batch is
  not an approximation. Every slot's stream is byte-identical to a
  dedicated single-adapter engine serving that adapter alone — greedy,
  sampled (the slot-key design makes the key stream invariant to batch
  composition), and through crash-recovery replay — and adapter 0 is
  bitwise the base model.
- **Streaming / embeddings** — per-token streams concatenate to exactly
  the non-streamed result and survive mid-stream cancel; embedding
  requests ride the same scheduler/metrics lifecycle without a KV slot.
"""

import queue
import threading
import time

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.models.transformer import (
    TransformerConfig,
    init_lora_bank,
    init_transformer,
)
from deeplearning4j_tpu.serving import (
    EmbeddingRequest,
    FaultInjector,
    QuotaExceeded,
    Request,
    RequestScheduler,
    RequestStatus,
    ServingEngine,
    TenantConfig,
    TenantRegistry,
)

pytestmark = pytest.mark.tenancy

needs_2_devices = pytest.mark.skipif(
    len(jax.devices()) < 2, reason="needs >= 2 devices for TP/sharding"
)

CFG = TransformerConfig(
    vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64, max_len=32
)
# the Pallas decode kernel cannot GSPMD-partition (see
# test_serving_tp.py) — the TP LoRA parity run compares dense-vs-dense
TP_CFG = TransformerConfig(
    vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
    max_len=32, decode_kernel=False,
)
_PARAMS = {}
_BANKS = {}


def _params(cfg=CFG, seed=0):
    key = (id(cfg), seed)
    if key not in _PARAMS:
        _PARAMS[key] = init_transformer(jax.random.key(seed), cfg)
    return _PARAMS[key]


def _bank(cfg=CFG, n_adapters=4, rank=2, seed=1):
    key = (id(cfg), n_adapters, rank, seed)
    if key not in _BANKS:
        _BANKS[key] = init_lora_bank(
            jax.random.key(seed), cfg, n_adapters=n_adapters, rank=rank
        )
    return _BANKS[key]


def _requests(n, seed=0, tenant_id="", adapter=0, max_new=6, prompt=None):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        p = (prompt if prompt is not None
             else rng.integers(0, CFG.vocab_size,
                               (int(rng.integers(3, 10)),)).astype(np.int32))
        out.append(Request(
            prompt=np.array(p), max_new=max_new, tenant_id=tenant_id,
            adapter=adapter, done=threading.Event(),
        ))
    return out


def _run(engine, reqs):
    for r in reqs:
        engine.submit(r)
    engine.run()
    return {r.id: engine.pop_result(r.id) for r in reqs}


def _run_ordered(engine, reqs):
    """Drive step-by-step, recording each request's completion rank
    (ties within one step share a rank — what matters for fairness is
    which scheduling WAVE a request lands in, not intra-step order)."""
    for r in reqs:
        engine.submit(r)
    rank, ranks = 0, {}
    while not engine.idle:
        engine.step()
        newly = [r for r in reqs if r.done.is_set() and r.id not in ranks]
        if newly:
            for r in newly:
                ranks[r.id] = rank
            rank += 1
    return ranks


# -- deficit-round-robin fairness ----------------------------------------


def _flood_and_victims(tagged=True):
    """``tagged=False`` blanks the tenant ids: the DRR tier keys by
    ``tenant_id`` whether or not a registry is attached, so the honest
    FIFO baseline is untagged traffic (one implicit tenant) — exactly
    what the pre-tenancy engine saw."""
    flood = _requests(12, seed=1, tenant_id="flood" if tagged else "")
    victims = [r for v in range(3)
               for r in _requests(
                   2, seed=10 + v,
                   tenant_id=f"victim{v}" if tagged else "")]
    return flood, victims


def _fair_registry():
    return TenantRegistry(
        [TenantConfig("flood", api_key="f")]
        + [TenantConfig(f"victim{v}", api_key=f"v{v}") for v in range(3)]
    )


def test_drr_flood_does_not_starve_victims():
    """12-request flood submitted ahead of 6 victim requests, 2 slots:
    under DRR the victims' completion ranks sit measurably ahead of
    FIFO's (where they drain strictly last), streams stay identical,
    and nobody is dropped."""
    def build(fair):
        tenancy = _fair_registry() if fair else None
        return ServingEngine(
            CFG, _params(), n_slots=2, temperature=0.0,
            scheduler=RequestScheduler(max_queue_depth=64, tenancy=tenancy),
            tenancy=tenancy,
        )

    flood_a, victims_a = _flood_and_victims(tagged=False)
    fifo_ranks = _run_ordered(build(fair=False), flood_a + victims_a)
    flood_b, victims_b = _flood_and_victims()
    drr_ranks = _run_ordered(build(fair=True), flood_b + victims_b)

    def mean_victim_rank(ranks, victims, total):
        return np.mean([ranks[r.id] for r in victims]) / max(ranks.values())

    fifo_pos = mean_victim_rank(fifo_ranks, victims_a, len(fifo_ranks))
    drr_pos = mean_victim_rank(drr_ranks, victims_b, len(drr_ranks))
    # FIFO: victims queue behind the whole flood (normalized rank near
    # 1); DRR: each round-robin visit serves a victim, so they land in
    # the front half of the completion order
    assert fifo_pos > 0.7, fifo_pos
    assert drr_pos < fifo_pos - 0.2, (drr_pos, fifo_pos)
    for r in flood_b + victims_b:
        assert r.status is RequestStatus.FINISHED
    # greedy decode is order-invariant: reordering must not touch bytes
    eng = ServingEngine(CFG, _params(), n_slots=2, temperature=0.0)
    flood_c, victims_c = _flood_and_victims()
    clean = _run(eng, flood_c + victims_c)
    drr_eng = build(fair=True)
    flood_d, victims_d = _flood_and_victims()
    drr_out = _run(drr_eng, flood_d + victims_d)
    for a, b in zip(flood_c + victims_c, flood_d + victims_d):
        np.testing.assert_array_equal(clean[a.id], drr_out[b.id])


def test_drr_weight_biases_share():
    """weight=3 vs weight=1 under symmetric floods: the heavy tenant's
    requests complete earlier on average (DRR credit is quantum *
    weight per visit). The quantum is shrunk below one request's token
    cost and the LIGHT tenant submits first (owning the rotation
    front), so only the weight can explain heavy finishing earlier."""
    tenancy = TenantRegistry([
        TenantConfig("heavy", api_key="h", weight=3.0),
        TenantConfig("light", api_key="l", weight=1.0),
    ])
    engine = ServingEngine(
        CFG, _params(), n_slots=2, temperature=0.0,
        scheduler=RequestScheduler(max_queue_depth=64, tenancy=tenancy,
                                   drr_quantum=8),
        tenancy=tenancy,
    )
    heavy = _requests(6, seed=2, tenant_id="heavy")
    light = _requests(6, seed=3, tenant_id="light")
    mixed = [r for pair in zip(light, heavy) for r in pair]
    ranks = _run_ordered(engine, mixed)
    assert (np.mean([ranks[r.id] for r in heavy])
            < np.mean([ranks[r.id] for r in light]))


# -- token-rate quotas ---------------------------------------------------


def test_quota_429_and_refill():
    """Token bucket: burst admits, then QuotaExceeded; the injected
    clock refills at ``rate``; an unmetered tenant is untouched
    throughout; rejections land in the per-tenant metrics."""
    now = [0.0]
    tenancy = TenantRegistry(
        [
            # each request below costs 8 prompt + 8 max_new = 16 tokens
            TenantConfig("metered", api_key="m", rate=16.0, burst=32.0),
            TenantConfig("open", api_key="o"),
        ],
        clock=lambda: now[0],
    )
    engine = ServingEngine(
        CFG, _params(), n_slots=2, temperature=0.0,
        scheduler=RequestScheduler(max_queue_depth=64, tenancy=tenancy),
        tenancy=tenancy,
    )
    prompt = np.arange(8, dtype=np.int32) % CFG.vocab_size

    def req(tid):
        return Request(prompt=prompt.copy(), max_new=8, tenant_id=tid)

    ok = [engine.submit(req("metered")) for _ in range(2)]  # 32 = burst
    assert len(ok) == 2
    with pytest.raises(QuotaExceeded):
        engine.submit(req("metered"))
    # the flooder's dry bucket must not gate anyone else
    engine.submit(req("open"))
    assert tenancy.bucket_level("metered") == pytest.approx(0.0)

    now[0] += 1.0  # +16 tokens: exactly one more request
    engine.submit(req("metered"))
    with pytest.raises(QuotaExceeded):
        engine.submit(req("metered"))

    engine.run()
    s = engine.metrics.summary()
    assert s["rejections"] == {"quota": 2}
    assert s["tenants"]["metered"]["n_rejected"] == 2
    assert s["tenants"]["metered"]["n_finished"] == 3
    assert s["tenants"]["open"]["n_finished"] == 1


def test_slo_burn_gauge_from_per_tenant_p99():
    """A tenant with a p99-TPOT SLO gets a derived
    ``serve_tenant_slo_burn{tenant}`` gauge at every /metrics render
    (observed p99 / objective); tenants without an SLO, or with no
    TPOT samples yet, publish nothing."""
    tenancy = TenantRegistry([
        TenantConfig("gold", api_key="g", slo_p99_tpot_s=0.001),
        TenantConfig("free", api_key="f"),
    ])
    engine = ServingEngine(
        CFG, _params(), n_slots=2, temperature=0.0,
        scheduler=RequestScheduler(max_queue_depth=64, tenancy=tenancy),
        tenancy=tenancy,
    )
    # SLO declared but no traffic yet -> no gauge line (a 0 would read
    # as a perfect SLO with zero samples)
    assert "serve_tenant_slo_burn{" not in engine.metrics.render_prometheus()

    prompt = np.arange(8, dtype=np.int32) % CFG.vocab_size
    for tid in ("gold", "free", "gold"):
        engine.submit(Request(prompt=prompt.copy(), max_new=8,
                              tenant_id=tid))
    engine.run()

    text = engine.metrics.render_prometheus()
    lines = [ln for ln in text.splitlines()
             if ln.startswith("serve_tenant_slo_burn{")]
    assert len(lines) == 1 and 'tenant="gold"' in lines[0]
    burn = float(lines[0].split()[-1])
    s = engine.metrics.summary()
    assert burn == pytest.approx(
        s["tenants"]["gold"]["tpot_p99_s"] / 0.001
    )
    assert s["tenants"]["gold"]["slo_burn"] == pytest.approx(burn)
    assert "slo_burn" not in s["tenants"]["free"]
    # config plumbing: from_json carries the SLO; validation rejects 0
    reg = TenantRegistry.from_json(
        [{"id": "t", "slo_p99_tpot_s": 0.25}]
    )
    assert reg.get("t").slo_p99_tpot_s == 0.25
    with pytest.raises(ValueError, match="slo_p99_tpot_s"):
        TenantConfig("bad", slo_p99_tpot_s=0.0)


def test_max_slots_caps_concurrency():
    """A max_slots=1 tenant never holds two KV slots at once even with
    the pool free, and still finishes everything."""
    tenancy = TenantRegistry([
        TenantConfig("capped", api_key="c", max_slots=1),
        TenantConfig("roomy", api_key="r"),
    ])
    engine = ServingEngine(
        CFG, _params(), n_slots=3, temperature=0.0,
        scheduler=RequestScheduler(max_queue_depth=64, tenancy=tenancy),
        tenancy=tenancy,
    )
    capped = _requests(3, seed=4, tenant_id="capped")
    roomy = _requests(3, seed=5, tenant_id="roomy")
    for r in capped + roomy:
        engine.submit(r)
    peak = 0
    while not engine.idle:
        engine.step()
        held = sum(
            1 for st in engine._slots
            if st is not None and st.req.tenant_id == "capped"
        )
        peak = max(peak, held)
    assert peak == 1
    for r in capped + roomy:
        assert r.status is RequestStatus.FINISHED


# -- batched LoRA parity -------------------------------------------------


def _mixed_reqs(adapters=(1, 2, 3, 0), seed=6, max_new=6):
    """One request per adapter, all on the SAME prompt so divergent
    streams can only come from the adapter rows."""
    rng = np.random.default_rng(seed)
    prompt = rng.integers(0, CFG.vocab_size, (7,)).astype(np.int32)
    return [
        Request(prompt=prompt.copy(), max_new=max_new, adapter=a)
        for a in adapters
    ]


def _lora_engine(cfg=CFG, bank=None, tp=None, **kw):
    kw.setdefault("temperature", 0.0)
    extra = {} if tp is None else {"tp": tp}
    return ServingEngine(
        cfg, _params(cfg), n_slots=4,
        lora_bank=_bank(cfg) if bank is None else bank,
        lora_parity=True, retry_backoff_s=0.001, max_backoff_s=0.004,
        **extra, **kw,
    )


def test_lora_mixed_batch_matches_single_adapter_engines_greedy():
    """THE parity bar: each slot of a mixed-adapter greedy batch is
    byte-identical to a dedicated engine serving that adapter alone —
    and the adapters do diverge (same prompt, distinct streams)."""
    reqs = _mixed_reqs()
    mixed = _run(_lora_engine(), reqs)
    streams = [tuple(mixed[r.id]) for r in reqs]
    assert len(set(streams)) == len(streams), "adapters failed to diverge"
    for r in reqs:
        solo_req = Request(prompt=r.prompt.copy(), max_new=r.max_new,
                           adapter=r.adapter)
        solo = _run(_lora_engine(), [solo_req])
        np.testing.assert_array_equal(mixed[r.id], solo[solo_req.id])


def test_lora_mixed_batch_matches_single_adapter_engines_sampled():
    """Sampled parity: slot keys are split in admission order and the
    per-token key is fold_in(slot_key, position) — invariant to batch
    composition. A dedicated adapter-i engine fed the SAME submission
    sequence (every request pinned to adapter i, so the key schedule
    matches) reproduces the mixed batch's adapter-i stream exactly."""
    reqs = _mixed_reqs(max_new=8)
    mixed = _run(_lora_engine(temperature=1.0, top_k=8), reqs)
    for idx, r in enumerate(reqs):
        pinned = [
            Request(prompt=q.prompt.copy(), max_new=q.max_new,
                    adapter=r.adapter)
            for q in reqs
        ]
        solo = _run(_lora_engine(temperature=1.0, top_k=8), pinned)
        np.testing.assert_array_equal(mixed[r.id], solo[pinned[idx].id])


def test_lora_adapter0_is_bitwise_base_model():
    """Adapter row 0 is the zero adapter: with the bank ATTACHED, every
    adapter-0 stream is bitwise the no-bank engine's — the probe that
    gates the whole subsystem, asserted end to end."""
    eng = _lora_engine()
    assert eng.n_adapters == 4  # parity probe passed, bank live
    reqs = _requests(5, seed=7, adapter=0, max_new=6)
    with_bank = _run(eng, reqs)
    clones = [Request(prompt=r.prompt.copy(), max_new=r.max_new)
              for r in reqs]
    base = _run(
        ServingEngine(CFG, _params(), n_slots=4, temperature=0.0), clones
    )
    for r, c in zip(reqs, clones):
        np.testing.assert_array_equal(with_bank[r.id], base[c.id])


def test_lora_crash_recovery_parity_sampled():
    """Mixed adapters through an engine crash (sampled, the harder
    case): replay recovery re-seats slot keys AND adapter indices, so
    the recovered streams are byte-identical to an unfaulted run."""
    reqs = _mixed_reqs(max_new=8)
    clean = _run(_lora_engine(temperature=1.0, top_k=8), reqs)
    reqs2 = [Request(prompt=r.prompt.copy(), max_new=r.max_new,
                     adapter=r.adapter) for r in reqs]
    inj = FaultInjector().plan("step", at=2, kind="crash")
    engine = _lora_engine(temperature=1.0, top_k=8, faults=inj)
    faulted = _run(engine, reqs2)
    assert engine.metrics.n_restarts == 1
    for a, b in zip(reqs, reqs2):
        np.testing.assert_array_equal(clean[a.id], faulted[b.id])
        assert b.status is RequestStatus.FINISHED


@needs_2_devices
def test_lora_tp2_parity():
    """Sharding the adapter bank with the TP column layout is invisible
    in the bytes: TP=2 mixed-adapter streams == TP=1's."""
    bank = _bank(TP_CFG)
    reqs = _mixed_reqs()
    base = _run(_lora_engine(TP_CFG, bank=bank, tp=1), reqs)
    reqs2 = [Request(prompt=r.prompt.copy(), max_new=r.max_new,
                     adapter=r.adapter) for r in reqs]
    eng = _lora_engine(TP_CFG, bank=bank, tp=2)
    sharded = _run(eng, reqs2)
    assert eng.n_adapters == 4
    for a, b in zip(reqs, reqs2):
        np.testing.assert_array_equal(base[a.id], sharded[b.id])


# -- SSE token streaming -------------------------------------------------


def _drain(q, timeout=30.0):
    toks, deadline = [], time.monotonic() + timeout
    while True:
        tok = q.get(timeout=max(deadline - time.monotonic(), 0.01))
        if tok is None:
            return toks
        toks.append(tok)


def test_streaming_tokens_concatenate_to_result():
    """A streamed request's per-token queue, concatenated, is exactly
    the generated tail of the non-streamed result — and the terminal
    status is visible BEFORE the sentinel arrives."""
    engine = ServingEngine(CFG, _params(), n_slots=2, temperature=0.0)
    reqs = _requests(3, seed=8, max_new=6)
    streamed = Request(prompt=reqs[0].prompt.copy(), max_new=6,
                       stream=queue.Queue())
    out = _run(engine, reqs)

    engine2 = ServingEngine(CFG, _params(), n_slots=2, temperature=0.0)
    engine2.submit(streamed)
    t = threading.Thread(target=engine2.run)
    t.start()
    toks = _drain(streamed.stream)
    assert streamed.status is RequestStatus.FINISHED  # set pre-sentinel
    t.join(timeout=30)
    np.testing.assert_array_equal(
        np.asarray(toks, np.int32), out[reqs[0].id][len(reqs[0].prompt):]
    )


def test_streaming_mid_cancel_drains_cleanly():
    """Cancel after two streamed tokens: the sentinel still arrives
    (bounded wait, no hang), status is CANCELLED, and an unrelated
    request in the same batch finishes untouched."""
    engine = ServingEngine(
        CFG, _params(), n_slots=2, temperature=0.0,
        faults=FaultInjector(delay_s=0.01),  # ~10ms/step: cancel lands
    )
    victim = Request(prompt=np.arange(5, dtype=np.int32), max_new=20,
                     stream=queue.Queue())
    bystander = _requests(1, seed=9, max_new=5)[0]
    engine.submit(victim)
    engine.submit(bystander)
    t = threading.Thread(target=engine.run)
    t.start()
    got = [victim.stream.get(timeout=30) for _ in range(2)]
    assert all(g is not None for g in got)
    assert engine.cancel(victim.id)
    rest = _drain(victim.stream)
    assert victim.status is RequestStatus.CANCELLED
    assert len(got) + len(rest) < 20
    t.join(timeout=30)
    assert bystander.status is RequestStatus.FINISHED


# -- embeddings through the serving lifecycle ----------------------------


class _StubEmbedder:
    """Minimal zoo-shaped model: the engine only needs
    ``get_word_vector(word) -> np.ndarray | None``."""

    def __init__(self, dim=4):
        self.dim = dim

    def get_word_vector(self, word):
        if word.startswith("oov"):
            return None
        rng = np.random.default_rng(abs(hash(word)) % 2**32)
        return rng.standard_normal(self.dim).astype(np.float32)


def test_embeddings_ride_the_scheduler():
    """Embedding requests share admission/metrics/lifecycle with
    generate traffic but never take a KV slot: they are served even
    when every slot is occupied; OOV words map to None; an unknown
    model FAILS that request alone."""
    engine = ServingEngine(
        CFG, _params(), n_slots=2, temperature=0.0,
        embedders={"stub": _StubEmbedder()},
    )
    gen = _requests(4, seed=10, max_new=6)  # 4 requests > 2 slots
    emb = EmbeddingRequest(words=("alpha", "oov_x", "beta"), model="stub",
                           done=threading.Event())
    bad = EmbeddingRequest(words=("alpha",), model="nope",
                           done=threading.Event())
    for r in gen:
        engine.submit(r)
    engine.submit(emb)
    engine.submit(bad)
    engine.run()

    assert emb.status is RequestStatus.FINISHED
    assert set(emb.result) == {"alpha", "oov_x", "beta"}
    assert emb.result["oov_x"] is None
    assert emb.result["alpha"].shape == (4,)
    assert bad.status is RequestStatus.FAILED
    assert "nope" in bad.error
    for r in gen:
        assert r.status is RequestStatus.FINISHED
    s = engine.metrics.summary()
    assert s["n_embeddings"] == 1
    assert "embedding_p50_s" in s


# -- chaos with tenancy --------------------------------------------------


def test_chaos_flood_with_tenancy_and_lora():
    """The whole subsystem at once: tenanted flood + victims, mixed
    adapters, an engine crash mid-flood — everything finishes, streams
    match a clean identically-tenanted run, and the per-tenant metrics
    block tells the story."""
    def build(faults=None):
        tenancy = _fair_registry()
        return ServingEngine(
            CFG, _params(), n_slots=2, temperature=0.0,
            scheduler=RequestScheduler(max_queue_depth=64, tenancy=tenancy),
            tenancy=tenancy, lora_bank=_bank(), lora_parity=True,
            faults=faults, retry_backoff_s=0.001, max_backoff_s=0.004,
        )

    def traffic():
        flood = _requests(8, seed=11, tenant_id="flood")
        for i, r in enumerate(flood):
            r.adapter = i % 4
        victims = [r for v in range(3)
                   for r in _requests(1, seed=20 + v,
                                      tenant_id=f"victim{v}")]
        return flood + victims

    reqs = traffic()
    clean = _run(build(), reqs)
    reqs2 = traffic()
    inj = (FaultInjector()
           .plan("step", at=3, kind="crash")
           .plan("step", at=9, kind="transient"))
    engine = build(faults=inj)
    faulted = _run(engine, reqs2)

    assert engine.metrics.n_restarts == 1
    for a, b in zip(reqs, reqs2):
        np.testing.assert_array_equal(clean[a.id], faulted[b.id])
        assert b.status is RequestStatus.FINISHED
    tenants = engine.metrics.summary()["tenants"]
    assert tenants["flood"]["n_finished"] == 8
    for v in range(3):
        assert tenants[f"victim{v}"]["n_finished"] == 1
