"""Config serde tests ≙ reference NeuralNetConfigurationTest /
MultiLayerNeuralNetConfigurationTest (JSON round-trip)."""

import jax.numpy as jnp

from deeplearning4j_tpu.nn import activations, conf, losses, weights
from deeplearning4j_tpu import rng


def test_layer_config_json_roundtrip():
    c = conf.LayerConfig(
        layer_type="rbm",
        n_in=784,
        n_out=500,
        activation="tanh",
        momentum_after={10: 0.9, 20: 0.99},
        visible_unit=conf.VisibleUnit.GAUSSIAN,
        hidden_unit=conf.HiddenUnit.RECTIFIED,
        k=3,
        dist=("normal", 0.0, 0.01),
        weight_init="distribution",
    )
    c2 = conf.LayerConfig.from_json(c.to_json())
    assert c2 == c


def test_multilayer_config_json_roundtrip():
    mc = conf.list_builder(
        conf.LayerConfig(activation="tanh", lr=1e-2),
        sizes=[3, 2],
        n_in=4,
        n_out=3,
        hidden_layer_type="rbm",
    )
    mc2 = conf.MultiLayerConfig.from_json(mc.to_json())
    assert mc2 == mc
    assert mc.n_layers == 3
    assert mc.confs[0].n_in == 4 and mc.confs[0].n_out == 3
    assert mc.confs[1].n_in == 3 and mc.confs[1].n_out == 2
    assert mc.confs[2].layer_type == "output"
    assert mc.confs[2].n_in == 2 and mc.confs[2].n_out == 3


def test_list_builder_overrides():
    mc = conf.list_builder(
        conf.LayerConfig(),
        sizes=[5],
        n_in=4,
        n_out=3,
        overrides={0: lambda c: c.replace(lr=0.5), 1: lambda c: c.replace(loss="MSE")},
    )
    assert mc.confs[0].lr == 0.5
    assert mc.confs[1].loss == "MSE"


def test_activation_registry():
    x = jnp.array([-2.0, 0.0, 2.0])
    for name in activations.names():
        y = activations.get(name)(x)
        assert y.shape == x.shape
    s = activations.get("softmax")(jnp.ones((2, 3)))
    assert jnp.allclose(s.sum(-1), 1.0)


def test_losses_basic():
    labels = jnp.array([[1.0, 0.0], [0.0, 1.0]])
    good = jnp.array([[0.9, 0.1], [0.1, 0.9]])
    bad = jnp.array([[0.1, 0.9], [0.9, 0.1]])
    for name in losses.names():
        lg = losses.get(name)(labels, good)
        assert jnp.isfinite(lg)
    assert losses.get("MCXENT")(labels, good) < losses.get("MCXENT")(labels, bad)
    assert losses.get("MSE")(labels, good) < losses.get("MSE")(labels, bad)


def test_fused_logits_loss_matches_unfused():
    import jax

    labels = jnp.array([[1.0, 0.0, 0.0], [0.0, 0.0, 1.0]])
    logits = jnp.array([[2.0, -1.0, 0.3], [0.1, 0.2, 1.5]])
    fused = losses.logits_loss("MCXENT", labels, logits)
    unfused = losses.get("MCXENT")(labels, jax.nn.softmax(logits, -1))
    assert jnp.allclose(fused, unfused, atol=1e-4)


def test_weight_init_schemes():
    ks = rng.KeyStream(0)
    for scheme in weights.SCHEMES:
        w = weights.init_weights(ks.next(), (64, 32), scheme)
        assert w.shape == (64, 32)
        if scheme == "zero":
            assert jnp.all(w == 0)
        else:
            assert jnp.std(w) > 0
    wn = weights.init_weights(ks.next(), (1000, 10), "normalized")
    assert abs(float(wn.mean())) < 1e-3  # centered, scaled by 1/fan_in
