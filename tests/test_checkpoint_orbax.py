"""Orbax async sharded checkpointing + hybrid mesh helpers.

Beyond the npz CheckpointManager (reference parity: ModelSavingActor
round saving): shard-local writes, async persistence, sharded restore.
"""

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.models.transformer import (
    TransformerConfig,
    init_transformer,
    place_transformer_params,
)
from deeplearning4j_tpu.parallel import mesh as mesh_lib
from deeplearning4j_tpu.parallel.checkpoint import AsyncShardedCheckpointManager

CFG = TransformerConfig(
    vocab_size=32, d_model=16, n_heads=2, n_layers=2, d_ff=32, max_len=16
)


@pytest.mark.slow
def test_async_sharded_save_restore_roundtrip(devices, tmp_path):
    mesh = mesh_lib.dp_mp_mesh(4, 2)
    params = place_transformer_params(
        mesh, init_transformer(jax.random.key(0), CFG)
    )
    mngr = AsyncShardedCheckpointManager(tmp_path / "ckpt", keep=3)
    assert mngr.maybe_save(1, params, meta={"loss": 1.5})
    mngr.wait()
    restored, meta = mngr.restore_latest(params)
    assert meta["step"] == 1 and meta["loss"] == 1.5
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert b.sharding == a.sharding  # laid out back onto the mesh
    mngr.close()


def test_retention_and_latest(devices, tmp_path):
    mesh = mesh_lib.dp_mp_mesh(4, 2)
    params = place_transformer_params(
        mesh, init_transformer(jax.random.key(1), CFG)
    )
    mngr = AsyncShardedCheckpointManager(tmp_path / "ckpt", keep=2)
    for s in (1, 2, 3, 4):
        mngr.maybe_save(s, params)
    mngr.wait()
    assert mngr.latest_step() == 4
    steps = sorted(
        int(p.name) for p in (tmp_path / "ckpt").iterdir() if p.name.isdigit()
    )
    assert steps == [3, 4]
    mngr.close()


def test_save_every_cadence(devices, tmp_path):
    mesh = mesh_lib.dp_mp_mesh(4, 2)
    params = place_transformer_params(
        mesh, init_transformer(jax.random.key(2), CFG)
    )
    mngr = AsyncShardedCheckpointManager(
        tmp_path / "ckpt", keep=5, save_every=2
    )
    results = [mngr.maybe_save(s, params) for s in (0, 1, 2, 3, 4)]
    mngr.wait()
    assert results == [True, False, True, False, True]
    mngr.close()


def test_hybrid_mesh_single_slice_collapse(devices):
    mesh = mesh_lib.hybrid_mesh({"data": 2, "model": 2}, dcn={"data": 2})
    assert mesh.axis_names == ("data", "model")
    assert mesh.shape["data"] == 4 and mesh.shape["model"] == 2


def test_hybrid_mesh_validates_device_count(devices):
    with pytest.raises(ValueError, match="need 16 devices"):
        mesh_lib.hybrid_mesh({"data": 8, "model": 2})


def test_hybrid_mesh_rejects_unknown_dcn_axis(devices):
    with pytest.raises(ValueError, match="not present in ici axes"):
        mesh_lib.hybrid_mesh({"data": 4}, dcn={"daat": 2})
