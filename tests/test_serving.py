"""Continuous-batching serving engine: parity, scheduling, backpressure.

The load-bearing property is the first test: a continuously-batched
greedy run — requests arriving staggered, sharing slots, decoding at
mixed depths — produces BYTE-IDENTICAL token streams to running each
request alone through ``transformer_generate``. That holds because the
decode math is row- and padding-invariant (masked cache rows contribute
exact zeros) and the engine samples through the same ``_top_k_filter``
family; it is the serving analogue of the speculative path's exactness
contract.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.models.transformer import (
    TransformerConfig,
    init_transformer,
    quantize_decode_params,
    transformer_generate,
)
from deeplearning4j_tpu.serving import (
    AdmissionError,
    Backpressure,
    KVSlotPool,
    Request,
    RequestScheduler,
    ServingEngine,
    ServingMetrics,
    ServingServer,
    run_request_trace,
)

CFG = TransformerConfig(
    vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64, max_len=32
)


def _params(cfg=CFG, seed=0):
    return init_transformer(jax.random.key(seed), cfg)


def _requests(n, seed=0, vocab=None, max_len=None, cfg=CFG):
    """n random requests with varied prompt lengths and budgets."""
    vocab = vocab or cfg.vocab_size
    max_len = max_len or cfg.max_len
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        tp = int(rng.integers(3, 10))
        out.append(Request(
            prompt=rng.integers(0, vocab, (tp,)).astype(np.int32),
            max_new=int(rng.integers(4, min(12, max_len - tp))),
        ))
    return out


def _reference_streams(cfg, params, reqs):
    """Each request decoded alone via the plain generate path."""
    gen = jax.jit(
        transformer_generate(cfg),
        static_argnames=("max_new", "temperature", "top_k"),
    )
    refs = {}
    for r in reqs:
        out = gen(params, jnp.asarray(r.prompt[None]), jax.random.key(0),
                  max_new=r.max_new, temperature=0.0)
        refs[r.id] = np.asarray(out)[0]
    return refs


def test_continuous_batching_matches_per_request_generate():
    """>= 8 staggered requests, 3 slots (forced interleaving + slot
    reuse): byte-identical streams vs per-request generate, and the
    batching must have actually overlapped requests (occupancy > 1)."""
    params = _params()
    reqs = _requests(9, seed=7)
    refs = _reference_streams(CFG, params, reqs)

    engine = ServingEngine(CFG, params, n_slots=3, temperature=0.0)
    trace = [(0.002 * i, r) for i, r in enumerate(reqs)]
    results = run_request_trace(engine, trace)

    assert set(results) == set(refs)
    for rid in refs:
        np.testing.assert_array_equal(results[rid], refs[rid])
    s = engine.metrics.summary()
    assert s["n_finished"] == len(reqs)
    assert s["occupancy_mean"] > 1.0, "requests never actually interleaved"
    # 9 requests through 3 slots: slots were reused
    assert s["steps"] < sum(r.max_new for r in reqs)


@pytest.mark.parametrize("mode", ["dense", "int8"])
def test_engine_parity_other_decode_paths(mode):
    """The parity contract holds on the dense fallback (decode_kernel
    off) and the fully-quantized int8-cache path (vector-pos scatter
    writes + per-row scale planes)."""
    import dataclasses

    if mode == "dense":
        cfg = dataclasses.replace(CFG, decode_kernel=False)
        params = _params(cfg)
    else:
        cfg = dataclasses.replace(
            CFG, decode_int8=True, n_kv_heads=2, rope=True
        )
        params = quantize_decode_params(_params(cfg), cfg)
    reqs = _requests(5, seed=3, cfg=cfg)
    refs = _reference_streams(cfg, params, reqs)
    engine = ServingEngine(cfg, params, n_slots=2, temperature=0.0)
    for r in reqs:
        engine.submit(r)
    results = engine.run()
    for rid in refs:
        np.testing.assert_array_equal(results[rid], refs[rid])


def test_slot_admission_and_retirement_ordering():
    """Admission is FIFO within a priority class into the lowest free
    slot; a retired slot is reused by the next queued request; priority
    0 jumps the FIFO queue. Token readback lags dispatch by exactly one
    horizon (the double buffer), so a request's tokens — and its
    retirement — land one ``step()`` after the dispatch that computed
    them; the step counts below pin that cadence."""
    params = _params()
    engine = ServingEngine(CFG, params, n_slots=2, temperature=0.0)
    rng = np.random.default_rng(0)

    def req(max_new, priority=1):
        return Request(
            prompt=rng.integers(0, 64, (4,)).astype(np.int32),
            max_new=max_new, priority=priority,
        )

    a, b, c, d = req(3), req(6), req(3), req(3, priority=0)
    for r in (a, b, c):
        engine.submit(r)
    engine.step()  # admits a -> slot 0, b -> slot 1; dispatch #1
    assert engine.pool.n_active == 2
    assert engine._slots[0].req is a and engine._slots[1].req is b
    engine.submit(d)  # priority 0: must admit before c
    engine.step()  # dispatch #2, sync #1 (a: 1 token)
    engine.step()  # dispatch #3 computes a's last token...
    assert a.id not in engine.results  # ...but it hasn't synced yet
    engine.step()  # sync #3: a (max_new=3) completes, slot 0 freed
    assert a.id in engine.results
    engine.step()  # d admitted into a's freed slot 0, ahead of c
    assert engine._slots[0].req is d
    assert engine.pool.n_active == 2
    engine.run()
    assert set(engine.results) == {r.id for r in (a, b, c, d)}


@pytest.mark.parametrize("horizon", [2, 4, 8])
def test_multi_step_horizon_parity(horizon):
    """The fused K-substep program preserves greedy byte-parity for
    every horizon (K=1 is the first test): EOS/max-len deactivation
    happens in-program via the active mask, and the host replays the
    same stopping rule at sync, so mid-horizon finishes truncate
    identically."""
    params = _params()
    reqs = _requests(8, seed=horizon)
    refs = _reference_streams(CFG, params, reqs)
    engine = ServingEngine(
        CFG, params, n_slots=3, temperature=0.0, decode_horizon=horizon,
    )
    trace = [(0.002 * i, r) for i, r in enumerate(reqs)]
    results = run_request_trace(engine, trace)
    for rid in refs:
        np.testing.assert_array_equal(results[rid], refs[rid])
    s = engine.metrics.summary()
    assert s["decode_horizon"] == horizon
    assert s["n_finished"] == len(reqs)
    # K tokens per dispatch: horizon count is bounded accordingly
    max_new_total = sum(r.max_new for r in reqs)
    assert s["steps"] <= -(-max_new_total // horizon) + len(reqs)


def test_bucketed_prefill_compile_bound():
    """Prompts of MANY distinct lengths compile at most one prefill
    program per power-of-two bucket (the engine pads prompts up to the
    bucket), each byte-identical to per-request generate — traffic
    diversity cannot trigger unbounded jit compilation."""
    params = _params()
    rng = np.random.default_rng(5)
    reqs = [
        Request(prompt=rng.integers(0, 64, (tp,)).astype(np.int32),
                max_new=4)
        for tp in range(1, 17)  # every length 1..16
    ]
    refs = _reference_streams(CFG, params, reqs)
    engine = ServingEngine(CFG, params, n_slots=4, temperature=0.0)
    for r in reqs:
        engine.submit(r)
    results = engine.run()
    for rid in refs:
        np.testing.assert_array_equal(results[rid], refs[rid])
    # 16 distinct lengths -> buckets {8, 16} only (min bucket 8)
    assert set(engine._prefill_fns) <= {8, 16}
    assert len(engine._prefill_fns) <= 2


def test_chunked_long_prompt_prefill_parity():
    """Prompts longer than the largest bucket stream through the
    chunked forward path (same bucket programs) and land bitwise with
    the one-shot prefill trajectory."""
    params = _params()
    rng = np.random.default_rng(6)
    reqs = [
        Request(prompt=rng.integers(0, 64, (tp,)).astype(np.int32),
                max_new=6)
        for tp in (9, 13, 17, 23)  # all > max bucket of 8
    ]
    refs = _reference_streams(CFG, params, reqs)
    engine = ServingEngine(
        CFG, params, n_slots=2, temperature=0.0, prefill_max_bucket=8,
    )
    for r in reqs:
        engine.submit(r)
    results = engine.run()
    for rid in refs:
        np.testing.assert_array_equal(results[rid], refs[rid])
    assert engine._max_bucket == 8
    assert set(engine._chunk_fns) <= {8}


def test_eos_retires_slot_early():
    params = _params()
    # find what greedy emits first, then use it as the EOS token
    r0 = Request(prompt=np.asarray([1, 2, 3], np.int32), max_new=8)
    engine = ServingEngine(CFG, params, n_slots=1, temperature=0.0)
    engine.submit(r0)
    first = int(engine.run()[r0.id][3])

    r1 = Request(prompt=np.asarray([1, 2, 3], np.int32), max_new=8,
                 eos_token=first)
    engine = ServingEngine(CFG, params, n_slots=1, temperature=0.0)
    engine.submit(r1)
    out = engine.run()[r1.id]
    assert len(out) == 4  # prompt + the EOS token, then retired
    assert out[-1] == first


def test_backpressure_and_admission_control():
    """submit raises Backpressure at max queue depth and AdmissionError
    for requests that can never fit a slot (both surfaced, not queued)."""
    sched = RequestScheduler(max_queue_depth=2, max_total_tokens=32)
    mk = lambda: Request(prompt=np.arange(4, dtype=np.int32), max_new=4)
    sched.submit(mk())
    sched.submit(mk())
    with pytest.raises(Backpressure):
        sched.submit(mk())
    with pytest.raises(AdmissionError):
        sched.submit(Request(prompt=np.zeros(30, np.int32), max_new=8))
    with pytest.raises(AdmissionError):
        sched.submit(Request(prompt=np.zeros(4, np.int32), max_new=4,
                             priority=99))
    # pop order: FIFO within class, strict priority across classes
    hi = Request(prompt=np.arange(3, dtype=np.int32), max_new=2, priority=0)
    sched2 = RequestScheduler(max_queue_depth=8)
    first, second = mk(), mk()
    sched2.submit(first)
    sched2.submit(second)
    sched2.submit(hi)
    assert sched2.pop() is hi
    assert sched2.pop() is first
    assert sched2.pop() is second
    assert sched2.pop() is None


def test_cache_pool_slot_reuse_no_realloc():
    """acquire/release recycles slot indices lowest-first over the ONE
    device allocation (the buffers are never re-created)."""
    pool = KVSlotPool(CFG, n_slots=3, max_total=CFG.max_len)
    buf_before = pool.caches
    s0, s1 = pool.acquire(), pool.acquire()
    assert (s0, s1) == (0, 1)
    pool.release(s0)
    assert pool.acquire() == 0  # lowest free index, reused
    assert pool.n_active == 2 and pool.n_free == 1
    with pytest.raises(ValueError):
        pool.release(2)  # never acquired
    assert pool.caches is buf_before  # pool itself never touched device
    assert pool.tpad >= CFG.max_len and pool.tpad % 8 == 0


def test_metrics_emission(tmp_path):
    """TTFT/TPOT/occupancy/queue-depth flow through MetricsWriter as
    JSONL and the summary exposes p50/p99."""
    from deeplearning4j_tpu.utils.metrics import MetricsWriter

    path = tmp_path / "serve.jsonl"
    writer = MetricsWriter(path)
    params = _params()
    engine = ServingEngine(
        CFG, params, n_slots=2, temperature=0.0,
        metrics=ServingMetrics(writer=writer),
    )
    for r in _requests(4, seed=11):
        engine.submit(r)
    engine.run()
    writer.close()

    records = MetricsWriter.read(path)
    tags = {r["tag"] for r in records}
    assert {"serve/ttft_seconds", "serve/tpot_seconds",
            "serve/occupancy", "serve/queue_depth"} <= tags
    s = engine.metrics.summary()
    for k in ("ttft_p50_s", "ttft_p99_s", "tpot_p50_s", "tpot_p99_s",
              "occupancy_mean"):
        assert k in s and np.isfinite(s[k])
    assert s["ttft_p50_s"] <= s["ttft_p99_s"]
    occ = [r["value"] for r in records if r["tag"] == "serve/occupancy"]
    assert len(occ) == s["steps"] and max(occ) <= 2


def test_http_server_roundtrip():
    """POST /v1/generate returns the same stream the engine computes;
    /metrics and /healthz answer; oversized requests get 400."""
    import json
    import urllib.error
    import urllib.request

    params = _params()
    engine = ServingEngine(CFG, params, n_slots=2, temperature=0.0)
    srv = ServingServer(engine, port=0).start()
    host, port = srv.address
    base = f"http://{host}:{port}"

    def post(payload):
        req = urllib.request.Request(
            f"{base}/v1/generate", data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=60) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    try:
        outs = [None, None]

        def worker(i):
            outs[i] = post({"prompt": [1 + i, 5, 9], "max_new": 5})

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i, (status, body) in enumerate(outs):
            assert status == 200
            assert body["tokens"][:3] == [1 + i, 5, 9]
            assert len(body["tokens"]) == 8
            ref = _reference_streams(
                CFG, params,
                [Request(prompt=np.asarray([1 + i, 5, 9], np.int32),
                         max_new=5)],
            )
            np.testing.assert_array_equal(
                body["tokens"], next(iter(ref.values()))
            )
        status, body = post({"prompt": [0] * 40, "max_new": 8})
        assert status == 400 and "budget" in body["error"]
        with urllib.request.urlopen(f"{base}/metrics.json", timeout=10) as r:
            m = json.loads(r.read())
        assert m["n_finished"] >= 2 and "ttft_p50_s" in m
        with urllib.request.urlopen(f"{base}/metrics", timeout=10) as r:
            assert "version=0.0.4" in r.headers["Content-Type"]
            prom = r.read().decode()
        assert 'serve_requests_total{outcome="finished"} 2' in prom
        assert "# TYPE serve_ttft_seconds histogram" in prom
        with urllib.request.urlopen(f"{base}/healthz", timeout=10) as r:
            hz = json.loads(r.read())
        assert hz["ok"] is True and hz["engine_alive"] is True
        assert hz["last_error"] is None and hz["restarts"] == 0
        with urllib.request.urlopen(f"{base}/readyz", timeout=10) as r:
            assert json.loads(r.read())["ready"] is True
    finally:
        srv.stop()
