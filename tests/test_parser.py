"""PCFG-CKY parser: real constituency structure for raw text.

≙ TreeParser.java (OpenNLP constituency parsing) + BinarizeTree
Transformer/CollapseUnaries — the VERDICT r1 gap: the raw-text path was
a right-branching fallback, making RNTN-on-raw-text structurally
trivial."""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp.parser import (
    CkyParser, Pcfg, bundled_treebank, default_parser,
)
from deeplearning4j_tpu.nlp.tree import TreeVectorizer, binarize, right_branching_tree


def _max_left_leaves(tree):
    """Largest left-child constituent (in leaves) anywhere in the tree.
    A pure right-branching tree scores exactly 1 — every left child is
    a single leaf — so anything >1 is structure the fallback cannot
    produce."""
    best = 0
    for node in tree.subtrees():
        if len(node.children) == 2:
            best = max(best, len(node.children[0].leaves()))
    return best


def test_bundled_treebank_parses():
    trees = bundled_treebank()
    assert len(trees) >= 25
    assert all(t.label == "S" for t in trees)


def test_cky_recovers_subject_pp_attachment():
    p = default_parser()
    toks = "the cat on the mat saw a dog".split()
    tree = p.parse(toks)
    assert tree is not None
    assert tree.words() == toks
    # the subject NP ("the cat on the mat", 5 words) is the LEFT child
    # of the top split — measurably non-right-branching
    assert len(tree.children[0].leaves()) == 5
    rb = binarize(right_branching_tree(toks))
    assert _max_left_leaves(rb) == 1
    assert _max_left_leaves(tree) >= 5


def test_cky_handles_unknown_words():
    p = default_parser()
    tree = p.parse("the wug saw a florp".split())
    assert tree is not None and tree.words() == ["the", "wug", "saw", "a", "florp"]


def test_fragments_empty_input_and_vectorizer_robustness():
    p = default_parser()
    # fragments parse to their best constituent (like the reference's
    # parser, which returns whatever top node OpenNLP produces)
    single = p.parse(["the"])
    assert single is not None and single.words() == ["the"]
    assert p.parse([]) is None
    trees = TreeVectorizer().trees("the. the cat saw a dog.")
    assert len(trees) == 2
    assert all(len(t.words()) >= 1 for t in trees)


def test_vectorizer_trees_are_structurally_nontrivial():
    trees = TreeVectorizer().trees(
        "the cat on the mat saw a dog. the man in the park read a book."
    )
    assert len(trees) == 2
    assert all(_max_left_leaves(t) >= 5 for t in trees)


@pytest.mark.slow
def test_rntn_trains_on_pcfg_parsed_raw_text():
    from deeplearning4j_tpu.models.rntn import RNTN

    trees = TreeVectorizer().trees(
        "the cat on the mat saw a dog. the woman with the ball watched the child."
    )
    assert all(_max_left_leaves(t) >= 5 for t in trees)
    model = RNTN(num_classes=2, dim=6, seed=0)
    losses = model.fit_trees(trees, epochs=2)
    assert np.isfinite(losses).all()


def test_default_pos_tagger_trained_on_treebank():
    from deeplearning4j_tpu.nlp.pos import default_tagger

    tagger = default_tagger()
    assert tagger.trained
    tags = dict(tagger.tag("the cat saw a dog".split()))
    assert tags["the"] == "DET" and tags["a"] == "DET"
    assert tags["cat"] == "NOUN" and tags["dog"] == "NOUN"
    assert tags["saw"] == "VERB"
    # OOV word goes through the rule backoff inside the HMM
    oov = dict(tagger.tag("the wug jumped".split()))
    assert oov["jumped"] == "VERB"
