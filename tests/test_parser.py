"""PCFG-CKY parser: real constituency structure for raw text.

≙ TreeParser.java (OpenNLP constituency parsing) + BinarizeTree
Transformer/CollapseUnaries — the VERDICT r1 gap: the raw-text path was
a right-branching fallback, making RNTN-on-raw-text structurally
trivial."""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp.parser import (
    CkyParser, Pcfg, bundled_treebank, default_parser,
)
from deeplearning4j_tpu.nlp.tree import TreeVectorizer, binarize, right_branching_tree


def _max_left_leaves(tree):
    """Largest left-child constituent (in leaves) anywhere in the tree.
    A pure right-branching tree scores exactly 1 — every left child is
    a single leaf — so anything >1 is structure the fallback cannot
    produce."""
    best = 0
    for node in tree.subtrees():
        if len(node.children) == 2:
            best = max(best, len(node.children[0].leaves()))
    return best


def test_bundled_treebank_parses():
    trees = bundled_treebank()
    # r5 grew the treebank ~10x (VERDICT r4 #7): relative clauses,
    # coordination, copulas, modals, passives, SBAR complements, ...
    assert len(trees) >= 200
    assert all(t.label == "S" for t in trees)


def test_cky_low_fallback_on_nontoy_sentences():
    """VERDICT r4 #7 acceptance: ordinary declarative English — with
    plenty of words the lexicon has never seen — must parse through
    real grammar productions, not the right-branching fallback. The
    bound is <20% fallback; at stamp time all 30 parse (0%)."""
    p = default_parser()
    sents = [
        "the engineer fixed the machine",
        "a lion chased the zebra near the river",
        "my sister wrote a poem about the sea",
        "the scientists said that the experiment failed",
        "the waiter who served the meal was friendly",
        "two tourists visited the museum and the castle",
        "the old sailor told the children a strange story",
        "she will not open the heavy door",
        "the kitten was sleeping under the warm blanket",
        "the soldiers marched slowly",
        "the painting that the artist sold was beautiful",
        "there is a spider on the wall",
        "he wanted to buy a new car",
        "the nurse helped the patient and the doctor",
        "the mountain is tall and quiet",
        "the students are writing essays",
        "the bread was baked by the baker",
        "the manager thought that the plan was good",
        "our neighbor walked from the station to the office",
        "the chef cooked a delicious dinner",
        "they should visit the ancient temple",
        "the singer sang happily",
        "a dolphin jumped over the wave",
        "the professor gave the lecture to the class",
        "the firefighters saved the family",
        "his brother became a pilot",
        "the librarian found the missing book",
        "the train left before the storm",
        "the gardener watered the flowers in the morning",
        "wolves hunt deer",
    ]
    fallbacks = sum(1 for s in sents if p.parse(s.split()) is None)
    assert fallbacks / len(sents) < 0.20, f"{fallbacks}/{len(sents)}"
    # and the parses carry real constituent structure, not a degenerate
    # single shape: a relative clause yields an SBAR-bearing subject
    t = p.parse("the waiter who served the meal was friendly".split())
    assert t is not None, "relative-clause sentence fell back entirely"
    labels = set()

    def walk(n):
        labels.add(n.label)
        for c in n.children:
            walk(c)

    walk(t)
    assert "SBAR" in labels or any(l.startswith("@") for l in labels)


def test_cky_recovers_subject_pp_attachment():
    p = default_parser()
    toks = "the cat on the mat saw a dog".split()
    tree = p.parse(toks)
    assert tree is not None
    assert tree.words() == toks
    # the subject NP ("the cat on the mat", 5 words) is the LEFT child
    # of the top split — measurably non-right-branching
    assert len(tree.children[0].leaves()) == 5
    rb = binarize(right_branching_tree(toks))
    assert _max_left_leaves(rb) == 1
    assert _max_left_leaves(tree) >= 5


def test_cky_handles_unknown_words():
    p = default_parser()
    tree = p.parse("the wug saw a florp".split())
    assert tree is not None and tree.words() == ["the", "wug", "saw", "a", "florp"]


def test_fragments_empty_input_and_vectorizer_robustness():
    p = default_parser()
    # fragments parse to their best constituent (like the reference's
    # parser, which returns whatever top node OpenNLP produces)
    single = p.parse(["the"])
    assert single is not None and single.words() == ["the"]
    assert p.parse([]) is None
    trees = TreeVectorizer().trees("the. the cat saw a dog.")
    assert len(trees) == 2
    assert all(len(t.words()) >= 1 for t in trees)


def test_vectorizer_trees_are_structurally_nontrivial():
    trees = TreeVectorizer().trees(
        "the cat on the mat saw a dog. the man in the park read a book."
    )
    assert len(trees) == 2
    assert all(_max_left_leaves(t) >= 5 for t in trees)


@pytest.mark.slow
def test_rntn_trains_on_pcfg_parsed_raw_text():
    from deeplearning4j_tpu.models.rntn import RNTN

    trees = TreeVectorizer().trees(
        "the cat on the mat saw a dog. the woman with the ball watched the child."
    )
    assert all(_max_left_leaves(t) >= 5 for t in trees)
    model = RNTN(num_classes=2, dim=6, seed=0)
    losses = model.fit_trees(trees, epochs=2)
    assert np.isfinite(losses).all()


def test_default_pos_tagger_trained_on_treebank():
    from deeplearning4j_tpu.nlp.pos import default_tagger

    tagger = default_tagger()
    assert tagger.trained
    tags = dict(tagger.tag("the cat saw a dog".split()))
    assert tags["the"] == "DET" and tags["a"] == "DET"
    assert tags["cat"] == "NOUN" and tags["dog"] == "NOUN"
    assert tags["saw"] == "VERB"
    # OOV word goes through the rule backoff inside the HMM
    oov = dict(tagger.tag("the wug jumped".split()))
    assert oov["jumped"] == "VERB"
