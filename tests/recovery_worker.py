"""Worker process for the failure-recovery test.

Run: python tests/recovery_worker.py <ckpt_dir> <total_steps> <save_every>
       [--status-url URL] [--final PATH] [--crash-after-none]

Deterministic training loop (data and key derived from the step index
alone) with periodic checkpoints, so a killed-and-restarted run replays
the exact remaining steps: restart == uninterrupted, bit-for-bit with a
stateless optimizer. Heartbeats POST to the master's statetracker REST
when --status-url is given (≙ WorkerActor.heartbeat).
"""

import argparse
import json
import os
import sys
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


def build():
    import jax.numpy as jnp
    import optax

    w_rng = np.random.default_rng(7)
    params = {
        "w1": jnp.asarray(w_rng.normal(size=(6, 12)).astype(np.float32) * 0.4),
        "b1": jnp.zeros((12,)),
        "w2": jnp.asarray(w_rng.normal(size=(12, 3)).astype(np.float32) * 0.4),
        "b2": jnp.zeros((3,)),
    }

    def loss_fn(p, xb, yb):
        h = jnp.tanh(xb @ p["w1"] + p["b1"])
        return optax.softmax_cross_entropy(h @ p["w2"] + p["b2"], yb).mean()

    return params, loss_fn


def batch_for_step(i: int):
    """Step-indexed deterministic data — replayable after restart."""
    rng = np.random.default_rng(1000 + i)
    x = rng.normal(size=(16, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
    return x, y


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("ckpt_dir")
    ap.add_argument("total_steps", type=int)
    ap.add_argument("save_every", type=int)
    ap.add_argument("--status-url", default=None)
    ap.add_argument("--final", default=None)
    ap.add_argument("--step-delay", type=float, default=0.0,
                    help="sleep per step — gives the kill-test parent a "
                    "window to observe checkpoints before completion")
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    from jax._src import xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)

    # NO persistent compile cache: this worker is SIGKILLed mid-run by
    # design (the kill-restart test), and a kill during a cache write
    # must never be able to poison the shared cache

    import jax.numpy as jnp
    import optax

    from deeplearning4j_tpu.parallel.checkpoint import CheckpointManager

    params, loss_fn = build()
    opt = optax.sgd(0.2)  # stateless -> params-only checkpoints resume exactly

    mgr = CheckpointManager(args.ckpt_dir, save_every=args.save_every, keep=3)
    start = 0
    restored = mgr.restore_latest(params)
    if restored is not None:
        params, meta = restored
        start = int(meta["step"])
        print(f"RESUMED_FROM={start}", flush=True)

    @jax.jit
    def step(p, x, y):
        l, g = jax.value_and_grad(loss_fn)(p, x, y)
        return optax.apply_updates(p, opt.update(g, opt.init(p))[0]), l

    loss = None
    for i in range(start + 1, args.total_steps + 1):
        x, y = batch_for_step(i)
        params, loss = step(params, jnp.asarray(x), jnp.asarray(y))
        loss = float(loss)
        if args.status_url:
            req = urllib.request.Request(
                f"{args.status_url}/statetracker/heartbeat",
                data=json.dumps(
                    {"worker": "w0", "meta": {"step": i}}
                ).encode(),
                method="POST",
                headers={"Content-Type": "application/json"},
            )
            urllib.request.urlopen(req, timeout=10).read()
        mgr.maybe_save(i, params, {"loss": loss})
        print(f"STEP={i}", flush=True)
        if args.step_delay:
            import time

            time.sleep(args.step_delay)

    if args.final:
        np.savez(
            args.final,
            **{k: np.asarray(v) for k, v in params.items()},
            loss=np.float64(loss),
        )
    print(f"LOSS={loss:.10f}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
