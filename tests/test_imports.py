"""Every module in the package imports cleanly.

PARITY.md maps reference components to modules by name; this walk keeps
those claims honest — a renamed/broken module fails here even if no
other test touches it.
"""

import importlib
import pkgutil

import deeplearning4j_tpu


def test_all_modules_import():
    failures = []
    for info in pkgutil.walk_packages(
        deeplearning4j_tpu.__path__, prefix="deeplearning4j_tpu."
    ):
        if info.name.endswith("__main__"):
            continue  # runs the CLI (argparse sys.exit) on import
        try:
            importlib.import_module(info.name)
        except Exception as e:  # noqa: BLE001 - collecting all failures
            failures.append((info.name, repr(e)))
    assert not failures, failures
