"""Viz + clustering tests ≙ reference TsneTest, BarnesHutTsneTest,
KDTreeTest, QuadTreeTest, VpTreeNodeTest, KMeans behavior."""

import numpy as np
import pytest

from deeplearning4j_tpu.clustering import KDTree, KMeans, QuadTree, VPTree
from deeplearning4j_tpu.plot.barnes_hut import BarnesHutTsne
from deeplearning4j_tpu.plot.plotter import NeuralNetPlotter, serve_tsne
from deeplearning4j_tpu.plot.tsne import Tsne


def _three_blobs(n_per=30, seed=0, d=10):
    rng = np.random.default_rng(seed)
    centers = rng.normal(0, 5, (3, d))
    pts = np.concatenate([c + rng.normal(0, 0.3, (n_per, d)) for c in centers])
    labels = np.repeat(np.arange(3), n_per)
    return pts.astype(np.float32), labels


def _cluster_quality(y, labels):
    # mean intra-cluster dist / mean inter-cluster dist (lower is better)
    intra, inter = [], []
    for i in range(len(y)):
        for j in range(i + 1, len(y)):
            d = np.linalg.norm(y[i] - y[j])
            (intra if labels[i] == labels[j] else inter).append(d)
    return np.mean(intra) / np.mean(inter)


def test_tsne_separates_blobs():
    x, labels = _three_blobs()
    y = Tsne(perplexity=15.0, n_iter=300, seed=1).calculate(x)
    assert y.shape == (90, 2)
    assert np.isfinite(y).all()
    assert _cluster_quality(y, labels) < 0.5


def test_barnes_hut_tsne_separates_blobs():
    x, labels = _three_blobs(n_per=20)
    y = BarnesHutTsne(perplexity=10.0, n_iter=150, seed=1).fit_transform(x)
    assert y.shape == (60, 2)
    assert np.isfinite(y).all()
    assert _cluster_quality(y, labels) < 0.6


def test_kmeans_recovers_blobs():
    x, labels = _three_blobs()
    km = KMeans(k=3, seed=2).fit(x)
    assert km.centroids.shape == (3, x.shape[1])
    # purity: majority label per cluster
    purity = 0
    for c in range(3):
        members = labels[km.labels_ == c]
        if len(members):
            purity += np.bincount(members).max()
    assert purity / len(labels) > 0.95


def test_kdtree_knn_matches_bruteforce():
    rng = np.random.default_rng(3)
    pts = rng.normal(size=(200, 4))
    tree = KDTree(pts)
    q = rng.normal(size=4)
    got = [i for _, i in tree.nearest(q, k=5)]
    want = np.argsort(np.linalg.norm(pts - q, axis=1))[:5].tolist()
    assert got == want
    # range query
    hits = tree.range(np.full(4, -0.5), np.full(4, 0.5))
    brute = [i for i, p in enumerate(pts) if np.all(p >= -0.5) and np.all(p <= 0.5)]
    assert sorted(hits) == sorted(brute)


def test_vptree_knn_matches_bruteforce():
    rng = np.random.default_rng(4)
    pts = rng.normal(size=(150, 6))
    tree = VPTree(pts)
    q = rng.normal(size=6)
    got = [i for _, i in tree.nearest(q, k=4)]
    want = np.argsort(np.linalg.norm(pts - q, axis=1))[:4].tolist()
    assert got == want


def test_quadtree_mass_and_forces():
    rng = np.random.default_rng(5)
    pts = rng.normal(size=(50, 2))
    tree = QuadTree.build(pts)
    assert tree.mass == 50
    assert np.allclose(tree.com, pts.mean(0), atol=1e-9)
    f = np.zeros(2)
    s = tree.compute_non_edge_forces(pts[0], theta=0.5, neg_f=f)
    assert np.isfinite(f).all() and s > 0


def test_plotter_outputs_files(tmp_path):
    p = NeuralNetPlotter(tmp_path)
    rng = np.random.default_rng(0)
    out1 = p.plot_weight_histograms({"W": rng.normal(size=(20, 10)), "b": rng.normal(size=10)})
    out2 = p.render_filters(rng.normal(size=(49, 9)))
    out3 = p.plot_activations(rng.random((16, 32)))
    for f in (out1, out2, out3):
        assert f.exists() and f.stat().st_size > 0


def test_tsne_render_endpoint():
    import json
    import urllib.request

    port = serve_tsne(["a", "b"], np.array([[0.0, 1.0], [2.0, 3.0]]))
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/api/coords") as r:
        data = json.loads(r.read())
    assert data[0]["word"] == "a" and data[1]["x"] == 2.0
