"""Grammar-constrained decoding + sampling-surface suite (PR 20).

Two load-bearing contracts. (1) The house parity bar, one more axis:
an engine armed with the sampling surface (``sampling_surface=True``)
routes EVERY decode dispatch through the masked step family — DFA mask
gather, logit-bias scatter, per-slot temperature/top_k/top_p, logprob
gather — yet unconstrained traffic streams BYTE-IDENTICAL tokens to
the plain engine, greedy AND sampled, across K∈{1,4}, paged block
tables, chunked-prefill piggyback, fault-injected crash recovery, and
TP=2. That holds because every surface feature folds out to the exact
plain computation at its neutral value (state 0, bias-free rows,
engine-default temp/top_k, top_p=1), and is enforced at construction
by a bitwise parity probe persisted through ``ProbeCache``.

(2) Validity: a request with a JSON-schema/regex ``response_format``
only ever emits DFA-permitted tokens — the mask lands BEFORE the draw
and the FSM advances in-program across all K substeps — so constrained
outputs parse and validate by construction, greedy and sampled,
including byte-identical replay through crash recovery (FSM state is
re-derived from ``gstate0`` + the emitted prefix at re-seat).
"""

import json
import os

import numpy as np
import pytest

import jax

from deeplearning4j_tpu.models.transformer import (
    TransformerConfig,
    init_transformer,
)
from deeplearning4j_tpu.serving import (
    FaultInjector,
    Request,
    ServingEngine,
)
from deeplearning4j_tpu.serving.grammar import (
    GrammarBudgetError,
    GrammarCache,
    GrammarTable,
    StopMatcher,
    compile_json_schema,
    compile_regex,
    default_token_bytes,
    schema_to_regex,
    validate_json_value,
)
from deeplearning4j_tpu.serving.scheduler import AdmissionError

pytestmark = pytest.mark.grammar

needs_2_devices = pytest.mark.skipif(
    len(jax.devices()) < 2, reason="needs >= 2 devices for TP/sharding"
)

CFG = TransformerConfig(
    vocab_size=128, d_model=64, n_heads=4, n_kv_heads=2, n_layers=2,
    d_ff=128, max_len=64, rope=True, decode_kernel=False,
)
EOS = 127
TOKEN_BYTES = default_token_bytes(CFG.vocab_size)
_PARAMS = {}


def _params(cfg=CFG, seed=0):
    key = (id(cfg), seed)
    if key not in _PARAMS:
        _PARAMS[key] = init_transformer(jax.random.key(seed), cfg)
    return _PARAMS[key]


def _engine(surface=False, n_slots=4, cfg=CFG, **kw):
    kw.setdefault("temperature", 0.0)
    kw.setdefault("max_total", 64)
    kw.setdefault("decode_horizon", 2)
    kw.setdefault("adaptive_horizon", True)
    kw.setdefault("prefill_max_bucket", 8)
    return ServingEngine(
        cfg, _params(cfg), n_slots=n_slots,
        sampling_surface=surface,
        retry_backoff_s=0.001, max_backoff_s=0.004, **kw,
    )


def _surface(**kw):
    eng = _engine(surface=True, **kw)
    assert eng._surface, "sampling surface silently fell back"
    return eng


def _requests(n=8, seed=1, max_new=6):
    rng = np.random.default_rng(seed)
    reqs = []
    for i in range(n):
        ln = int(rng.integers(3, 40)) if i % 3 else 36
        p = ((1 + np.arange(ln)) % 127).astype(np.int32)
        reqs.append(Request(id=f"r{i}", prompt=p, max_new=max_new))
    return reqs


def _clone(reqs):
    return [Request(id=r.id, prompt=np.asarray(r.prompt).copy(),
                    max_new=r.max_new) for r in reqs]


def _run(engine, reqs, **run_kw):
    for r in reqs:
        engine.submit(r)
    engine.run(**run_kw)
    return {r.id: np.asarray(engine.results[r.id]) for r in reqs}


def _assert_same(a, b):
    assert a.keys() == b.keys()
    for k in a:
        np.testing.assert_array_equal(a[k], b[k])


def _generated(res, req):
    """Generated span of a full-sequence result (prompt and trailing
    EOS stripped)."""
    toks = [int(t) for t in np.asarray(res)[len(req.prompt):]]
    if toks and toks[-1] == req.eos_token:
        toks = toks[:-1]
    return toks


def _decode(toks):
    return bytes(t for t in toks if t < 256).decode("latin-1")


# -- grammar units -------------------------------------------------------


def test_regex_dfa_token_permissions():
    """The compiled DFA permits exactly the byte alternatives at each
    state, EOS only in accepting states."""
    cg = compile_regex("(yes|no)", TOKEN_BYTES, EOS)
    start = cg.start
    permitted = {t for t in range(128) if cg.trans[start, t] >= 0}
    assert permitted == {ord("y"), ord("n")}
    s = start
    for b in b"no":
        assert cg.trans[s, b] >= 0
        s = int(cg.trans[s, b])
    assert cg.accepting[s]
    assert cg.trans[s, EOS] == s, "EOS must self-loop at accepting"
    assert cg.trans[start, EOS] < 0, "EOS permitted before accepting"


def test_schema_to_regex_and_validator():
    schema = {
        "type": "object",
        "properties": {
            "ok": {"type": "boolean"},
            "tag": {"enum": ["a", "b"]},
        },
        "required": ["ok", "tag"],
    }
    pat = schema_to_regex(schema)
    cg = compile_json_schema(schema, TOKEN_BYTES, EOS)
    assert cg.n_states > 1
    assert pat.startswith("\\{")
    assert validate_json_value({"ok": True, "tag": "a"}, schema)
    assert not validate_json_value({"ok": 1, "tag": "a"}, schema)
    assert not validate_json_value({"ok": True, "tag": "z"}, schema)


def test_grammar_cache_memory_and_disk(tmp_path):
    """Fresh compile is a miss; the second lookup hits memory; a new
    cache instance over the same directory hits disk."""
    path = str(tmp_path / "grammars")
    c1 = GrammarCache(path)
    cg1, how1 = c1.get_or_compile("regex", "(a|b)c*", TOKEN_BYTES, EOS)
    assert how1 == "miss"
    cg2, how2 = c1.get_or_compile("regex", "(a|b)c*", TOKEN_BYTES, EOS)
    assert how2 == "hit" and cg2 is cg1
    c2 = GrammarCache(path)
    cg3, how3 = c2.get_or_compile("regex", "(a|b)c*", TOKEN_BYTES, EOS)
    assert how3 == "hit", "on-disk entry not found by a fresh cache"
    np.testing.assert_array_equal(cg3.trans, cg1.trans)
    np.testing.assert_array_equal(cg3.mask_words, cg1.mask_words)


def test_grammar_table_seat_release_evict():
    """Absolute-state seating: refcounted re-seat, LRU eviction of
    refcount-0 grammars under pressure, budget error when every row is
    pinned, and the all-permitted sentinel in row 0."""
    a = compile_regex("aaaa", TOKEN_BYTES, EOS)
    b = compile_regex("bbbb", TOKEN_BYTES, EOS)
    big = compile_regex("cccc", TOKEN_BYTES, EOS)
    assert big.n_states == a.n_states  # same shape, different bytes
    # capacity sized so a + b fill every non-sentinel row
    gt = GrammarTable(1 + a.n_states + b.n_states, CFG.vocab_size)
    assert gt.allows(0, 5) and gt.advance(0, 5) == 0  # sentinel
    sa = gt.seat(a)
    assert sa >= 1
    assert gt.seat(a) == sa, "re-seat must return the same start"
    v0 = gt.version
    gt.release(a.key)
    gt.release(a.key)
    # refcount 0 but still seated: rows stay until pressure evicts
    assert gt.base_of(a.key) is not None
    gt.seat(b)
    gt.seat(big)  # must evict a (refcount 0) to fit
    assert gt.base_of(a.key) is None
    assert gt.version > v0
    # everything pinned now: one more grammar cannot fit
    with pytest.raises(GrammarBudgetError):
        gt.seat(compile_regex("dddd", TOKEN_BYTES, EOS))
    # a DFA larger than capacity - 1 is over budget outright
    with pytest.raises(GrammarBudgetError):
        gt.seat(compile_regex("e" * (gt.capacity + 4),
                              TOKEN_BYTES, EOS))


def test_stop_matcher_holdback_and_flush():
    """Tokens that could begin a stop match are held back; a match
    drops the held tokens and reports the stripped length; flush
    releases the hold-back on other terminations."""
    m = StopMatcher([[5, 6]])
    assert m.push(1) == ([1], 0)
    assert m.push(5) == ([], 0), "possible stop prefix must be held"
    assert m.push(6) == ([], 2), "match strips the stop sequence"
    m2 = StopMatcher([[5, 6]])
    m2.push(5)
    assert m2.push(7) == ([5, 7], 0), "failed prefix is released"
    m3 = StopMatcher([[5, 6]])
    m3.push(5)
    assert m3.flush() == [5]


def test_request_field_validation():
    p = np.arange(4, dtype=np.int32)
    with pytest.raises(AdmissionError):
        Request(prompt=p, max_new=2, temperature=-0.5)
    with pytest.raises(AdmissionError):
        Request(prompt=p, max_new=2, top_k=0)
    with pytest.raises(AdmissionError):
        Request(prompt=p, max_new=2, top_p=0.0)
    with pytest.raises(AdmissionError):
        Request(prompt=p, max_new=2, logit_bias={i: 1.0 for i in range(9)})
    with pytest.raises(AdmissionError):
        Request(prompt=p, max_new=2, stop=[[1]] * 5)
    with pytest.raises(AdmissionError):
        Request(prompt=p, max_new=2, response_format={"type": "nope"})
    r = Request(prompt=p, max_new=2, top_logprobs=3)
    assert r.logprobs, "top_logprobs must imply logprobs"
    assert r.uses_sampling_surface
    assert not Request(prompt=p, max_new=2).uses_sampling_surface


# -- admission gates -----------------------------------------------------


def test_plain_engine_rejects_surface_requests():
    eng = _engine()
    with pytest.raises(AdmissionError):
        eng.submit(Request(prompt=np.arange(4, dtype=np.int32),
                           max_new=2, top_p=0.5))


def test_constrained_requires_eos_token():
    eng = _surface()
    with pytest.raises(AdmissionError):
        eng.submit(Request(
            prompt=np.arange(4, dtype=np.int32), max_new=4,
            response_format={"type": "regex", "regex": "(yes|no)"},
        ))


def test_approx_top_k_disables_surface():
    """lax.approx_max_k reorders ties, so the surface refuses to arm
    over it instead of silently breaking byte parity."""
    eng = _engine(surface=True, temperature=0.9, top_k=8,
                  approx_top_k=True)
    assert not eng._surface


def test_compile_budget_overflow_rejected():
    """A grammar whose DFA exceeds the table budget 400s at submit and
    is counted as a compile error — the engine stays healthy."""
    eng = _surface(grammar_states=8)
    with pytest.raises(AdmissionError):
        eng.submit(Request(
            prompt=np.arange(4, dtype=np.int32), max_new=8,
            eos_token=EOS,
            response_format={"type": "regex", "regex": "a" * 64},
        ))
    assert eng.metrics._c_grammar_compiles.value(result="error") == 1
    # the engine still serves after the rejection
    got = _run(eng, _requests(n=2))
    assert len(got) == 2


def test_compile_cache_hit_miss_metrics():
    eng = _surface()
    rf = {"type": "regex", "regex": "(yes|no)"}
    reqs = [Request(id=f"c{i}", prompt=np.arange(4, dtype=np.int32),
                    max_new=8, eos_token=EOS, response_format=rf)
            for i in range(3)]
    _run(eng, reqs)
    m = eng.metrics._c_grammar_compiles
    assert m.value(result="miss") == 1
    assert m.value(result="hit") == 2


# -- tentpole: unconstrained byte parity through the masked family -------


@pytest.mark.parametrize("temperature", [0.0, 0.9])
def test_unconstrained_byte_parity(temperature):
    """Plain traffic through a surface engine is byte-identical to the
    plain engine — every fold-out (state 0, no bias, default sampler)
    is exact, greedy and sampled."""
    reqs = _requests()
    ref = _run(_engine(temperature=temperature), _clone(reqs))
    eng = _surface(temperature=temperature)
    got = _run(eng, _clone(reqs))
    _assert_same(ref, got)
    assert eng._masked_step_fns, "masked family never dispatched"


@pytest.mark.parametrize("temperature", [0.0, 0.9])
def test_unconstrained_piggyback_parity(temperature):
    """Surface + chunked-prefill piggyback: the masked piggyback
    program keeps both parity bars at once."""
    reqs = _requests()
    ref = _run(_engine(temperature=temperature), _clone(reqs))
    eng = _surface(temperature=temperature, piggyback=True)
    assert eng._piggyback
    got = _run(eng, _clone(reqs))
    _assert_same(ref, got)
    assert eng.metrics.n_prefill_chunks > 0
    assert eng._masked_piggyback_fns, "masked piggyback never compiled"


@pytest.mark.slow
@pytest.mark.parametrize("temperature", [0.0, 0.9])
@pytest.mark.parametrize("paged", [False, True])
@pytest.mark.parametrize("horizon", [1, 4])
def test_unconstrained_parity_grid(temperature, paged, horizon):
    """The heavy grid: K∈{1,4} x paged on/off x greedy/sampled."""
    kw = dict(temperature=temperature, decode_horizon=horizon)
    if paged:
        kw.update(paged=True, block_size=8)
    reqs = _requests()
    ref = _run(_engine(**kw), _clone(reqs))
    eng = _surface(**kw)
    if paged:
        assert eng._paged
    got = _run(eng, _clone(reqs))
    _assert_same(ref, got)


@needs_2_devices
@pytest.mark.parametrize("temperature", [0.0, 0.9])
def test_tp2_parity_and_constrained(temperature):
    """TP=2 surface engine vs single-chip plain engine: same bytes for
    plain traffic, and constrained requests stay valid under TP."""
    reqs = _requests()
    ref = _run(_engine(temperature=temperature), _clone(reqs))
    eng = _surface(temperature=temperature, tp=2)
    assert eng.tp == 2, "TP parity probe fell back to tp=1"
    got = _run(eng, _clone(reqs))
    _assert_same(ref, got)
    r = Request(prompt=np.arange(4, dtype=np.int32), max_new=12,
                eos_token=EOS,
                response_format={"type": "regex", "regex": "(yes|no)"})
    res = _run(eng, [r])
    assert _decode(_generated(res[r.id], r)) in ("yes", "no")


# -- constrained decoding ------------------------------------------------


@pytest.mark.parametrize("temperature", [None, 0.9])
def test_constrained_tokens_all_dfa_permitted(temperature):
    """Every emitted token of a constrained stream is permitted by the
    DFA at its state, and the stream ends in an accepting state —
    greedy and sampled."""
    eng = _surface(temperature=0.0)
    r = Request(prompt=np.arange(4, dtype=np.int32), max_new=20,
                eos_token=EOS, temperature=temperature,
                response_format={"type": "regex",
                                 "regex": "(yes|no|maybe)!?"})
    res = _run(eng, [r])
    toks = _generated(res[r.id], r)
    assert toks, "constrained stream emitted nothing"
    cg = r._grammar
    s = cg.start
    for t in toks:
        assert cg.trans[s, t] >= 0, f"token {t} not permitted at {s}"
        s = int(cg.trans[s, t])
    assert cg.accepting[s]
    assert _decode(toks) in ("yes", "no", "maybe",
                             "yes!", "no!", "maybe!")


@pytest.mark.parametrize("temperature", [None, 0.9])
def test_constrained_json_schema_parses_and_validates(temperature):
    schema = {
        "type": "object",
        "properties": {
            "ok": {"type": "boolean"},
            "tag": {"enum": ["a", "bb"]},
        },
        "required": ["ok", "tag"],
    }
    eng = _surface(temperature=0.0)
    r = Request(prompt=np.arange(4, dtype=np.int32), max_new=30,
                eos_token=EOS, temperature=temperature,
                response_format={"type": "json_schema",
                                 "schema": schema})
    res = _run(eng, [r])
    value = json.loads(_decode(_generated(res[r.id], r)))
    assert validate_json_value(value, schema)


@pytest.mark.slow
@pytest.mark.parametrize("temperature", [None, 0.9])
def test_twenty_seeded_schemas_validate(temperature):
    """20 seeded schemas from the supported subset, decoded greedy AND
    sampled — every output parses as JSON and validates."""
    rng = np.random.default_rng(7)

    def rand_leaf():
        kind = rng.integers(0, 4)
        if kind == 0:
            return {"type": "boolean"}
        if kind == 1:
            n = int(rng.integers(2, 4))
            return {"enum": [
                "".join(chr(97 + int(c))
                        for c in rng.integers(0, 26, rng.integers(1, 4)))
                for _ in range(n)
            ]}
        if kind == 2:
            return {"const": int(rng.integers(0, 100))}
        return {"type": "null"}

    def rand_schema():
        props = {}
        for j in range(int(rng.integers(1, 3))):
            name = "".join(chr(97 + int(c))
                           for c in rng.integers(0, 26, 2)) + str(j)
            if rng.integers(0, 4) == 0:
                props[name] = {"type": "array", "items": rand_leaf(),
                               "minItems": 1, "maxItems": 2}
            else:
                props[name] = rand_leaf()
        return {"type": "object", "properties": props,
                "required": list(props)}

    schemas = [rand_schema() for _ in range(20)]
    eng = _surface(temperature=0.0, max_total=64)
    reqs = [
        Request(id=f"s{i}", prompt=np.arange(3, dtype=np.int32),
                max_new=52, eos_token=EOS, temperature=temperature,
                response_format={"type": "json_schema", "schema": sc})
        for i, sc in enumerate(schemas)
    ]
    res = _run(eng, reqs)
    for r, sc in zip(reqs, schemas):
        value = json.loads(_decode(_generated(res[r.id], r)))
        assert validate_json_value(value, sc), (sc, value)


# -- sampling controls ---------------------------------------------------


def test_stop_sequence_truncates_exactly():
    """A stop sequence taken from the greedy reference stream truncates
    the output right before the match and counts a stop hit."""
    eng = _engine()
    base = Request(id="b", prompt=np.arange(8, dtype=np.int32),
                   max_new=8)
    ref = _generated_plain(_run(eng, [base])["b"], base)
    assert len(ref) == 8
    stop = ref[3:5]
    # truncation point = FIRST occurrence of the pair in the stream
    # (greedy streams may repeat)
    cut = next(i for i in range(len(ref) - 1)
               if ref[i:i + 2] == stop)
    eng2 = _surface()
    r = Request(id="s", prompt=np.arange(8, dtype=np.int32),
                max_new=8, stop=[stop])
    got = _generated_plain(_run(eng2, [r])["s"], r)
    assert got == ref[:cut], "stream must end right before the match"
    assert eng2.metrics._c_stop_hits.value() == 1


def _generated_plain(res, req):
    return [int(t) for t in np.asarray(res)[len(req.prompt):]]


def test_logit_bias_forces_token():
    eng = _surface()
    r = Request(prompt=np.arange(4, dtype=np.int32), max_new=5,
                logit_bias={7: 1000.0})
    got = _generated_plain(_run(eng, [r])[r.id], r)
    assert got == [7] * 5


def test_logprobs_records():
    """Per-token logprobs ride the packed aux tensor: one record per
    generated token, chosen-token logprob equals the top alternative
    under greedy, alternatives sorted descending."""
    eng = _surface()
    r = Request(prompt=np.arange(4, dtype=np.int32), max_new=6,
                logprobs=True, top_logprobs=3)
    got = _generated_plain(_run(eng, [r])[r.id], r)
    recs = r.logprobs_out
    assert recs is not None and len(recs) == len(got) == 6
    for tok, rec in zip(got, recs):
        assert rec["token"] == tok
        assert rec["logprob"] <= 0.0
        tops = rec["top_logprobs"]
        assert len(tops) == 3
        lps = [t["logprob"] for t in tops]
        assert lps == sorted(lps, reverse=True)
        # greedy: the chosen token IS the argmax
        assert tops[0]["token"] == tok
        assert tops[0]["logprob"] == pytest.approx(rec["logprob"])


def test_per_request_temperature_and_topk_override():
    """temperature=0 / top_k=1 overrides on a sampled engine reproduce
    the greedy engine's bytes — the traced per-slot vectors really
    steer the draw."""
    ref_eng = _engine(temperature=0.0)
    reqs = _requests(n=4)
    ref = _run(ref_eng, _clone(reqs))
    eng = _surface(temperature=0.9)
    greedy = [Request(id=r.id, prompt=np.asarray(r.prompt).copy(),
                      max_new=r.max_new, temperature=0.0)
              for r in reqs]
    _assert_same(ref, _run(eng, greedy))
    eng2 = _surface(temperature=0.9)
    topk1 = [Request(id=r.id, prompt=np.asarray(r.prompt).copy(),
                     max_new=r.max_new, top_k=1)
             for r in reqs]
    _assert_same(ref, _run(eng2, topk1))


def test_top_p_nucleus_collapses_to_greedy():
    """A vanishingly small top_p keeps only the argmax in the nucleus,
    so a sampled request reproduces greedy bytes."""
    ref = _run(_engine(temperature=0.0), _requests(n=4))
    eng = _surface(temperature=0.9)
    reqs = [Request(id=f"r{i}", prompt=r.prompt, max_new=r.max_new,
                    top_p=1e-9)
            for i, r in enumerate(_requests(n=4))]
    _assert_same(ref, _run(eng, reqs))


# -- crash recovery ------------------------------------------------------


@pytest.mark.parametrize("temperature", [0.0, 0.9])
@pytest.mark.parametrize("crash_at", [2, 4])
def test_crash_recovery_constrained_byte_parity(temperature, crash_at):
    """Seeded crash mid-generation with constrained + stop + bias +
    logprobs traffic in flight: recovery re-seats FSM states (replayed
    from gstate0 over the emitted prefix), stop buffers, and bias rows,
    and the streams are byte-identical to the no-fault run."""
    schema = {"type": "object",
              "properties": {"k": {"enum": ["x", "yy", "zzz"]}},
              "required": ["k"]}

    def make_reqs():
        reqs = _requests(n=4, max_new=8)
        reqs.append(Request(
            id="cons", prompt=np.arange(4, dtype=np.int32), max_new=20,
            eos_token=EOS, temperature=temperature or None,
            response_format={"type": "json_schema", "schema": schema},
        ))
        reqs.append(Request(
            id="bias", prompt=np.arange(6, dtype=np.int32), max_new=6,
            logit_bias={9: 5.0}, logprobs=True,
        ))
        return reqs

    ref = _run(_surface(temperature=temperature), make_reqs())
    faults = FaultInjector().plan("step", crash_at, "crash")
    eng = _surface(temperature=temperature, faults=faults)
    got = _run(eng, make_reqs(), max_restarts=5)
    _assert_same(ref, got)
    assert eng.metrics.n_restarts >= 1, "crash never fired"
    value = json.loads(_decode(_generated(
        got["cons"],
        Request(id="x", prompt=np.arange(4, dtype=np.int32),
                max_new=20, eos_token=EOS),
    )))
    assert validate_json_value(value, schema)


# -- compile surface + probe cache ---------------------------------------


def test_masked_compile_surface_bounded():
    """The live masked families stay inside the audited expected
    surface for the same geometry."""
    from deeplearning4j_tpu.analysis.programs import (
        ServingGeometry,
        expected_surface,
        live_engine_families,
    )

    eng = _surface(piggyback=True)
    _run(eng, _requests())
    geom = ServingGeometry(
        n_slots=eng.n_slots, max_total=eng.max_total,
        temperature=eng.temperature, top_k=eng.top_k,
        approx_top_k=eng.approx_top_k,
        decode_horizon=eng.decode_horizon, adaptive_horizon=True,
        prefill_max_bucket=eng._max_bucket,
        sampling_surface=True,
    )
    exp = expected_surface(CFG, geom)
    live = live_engine_families(eng)
    assert live["masked_step"], "no masked program ever compiled"
    assert live["masked_step"] <= exp["masked_step"]
    assert live["masked_piggyback_step"] <= exp["masked_piggyback_step"]
    assert live["paged_masked_step"] == set()
    assert "gstate_set" in exp["singletons"]


def test_masked_parity_probe_cached_across_engines(tmp_path):
    """The construction-time masked-parity verdict persists through
    ProbeCache: a second engine with the same geometry constructs with
    zero probe dispatches."""
    path = str(tmp_path / "probes.json")
    e1 = _surface(probe_cache=path)
    assert "masked_parity" in e1.probes_run
    assert os.path.exists(path)
    e2 = _surface(probe_cache=path)
    assert "masked_parity" in e2.probes_from_cache
    assert e2.probes_run == []
