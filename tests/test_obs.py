"""Observability subsystem: tracer export validity, Prometheus text
format, bounded reservoirs, profiling trigger, structured logs.

The contracts under test are the ones the serving hot path leans on:

- a DISABLED tracer records exactly zero events (the engine ships with
  tracing off; the guard pins that "off" means off, not "cheap"),
- an ENABLED tracer produces structurally valid Chrome-trace JSON —
  per-track spans properly nested, metadata tracks present — that
  Perfetto/chrome://tracing will load,
- ``GET /metrics`` output parses as Prometheus text exposition 0.0.4
  and carries every family the serving dashboards scrape,
- latency series stay bounded (Algorithm R reservoir) while their
  n/total/min/max aggregates stay exact.
"""

import json
import logging
import math
import re
from io import StringIO

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.models.transformer import (
    TransformerConfig,
    init_transformer,
)
from deeplearning4j_tpu.obs import (
    MetricsRegistry,
    ProfileTrigger,
    Reservoir,
    Tracer,
    configure_json_logging,
)
from deeplearning4j_tpu.obs.trace import ENGINE_TRACK, SCHEDULER_TRACK
from deeplearning4j_tpu.serving import (
    Request,
    ServingEngine,
    ServingServer,
    run_request_trace,
)
from deeplearning4j_tpu.serving.metrics import PHASES, ServingMetrics

CFG = TransformerConfig(
    vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64, max_len=32
)


def _params(seed=0):
    return init_transformer(jax.random.key(seed), CFG)


def _requests(n, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        tp = int(rng.integers(3, 10))
        out.append(Request(
            prompt=rng.integers(0, CFG.vocab_size, (tp,)).astype(np.int32),
            max_new=int(rng.integers(4, 12)),
        ))
    return out


@pytest.fixture(scope="module")
def traced_run():
    """One traced serving run shared by the export/structure tests:
    8 staggered requests through 3 slots, fused horizon 2, tracing ON."""
    tracer = Tracer(enabled=True, capacity=1 << 14)
    engine = ServingEngine(
        CFG, _params(), n_slots=3, temperature=0.0, decode_horizon=2,
        tracer=tracer,
    )
    results = run_request_trace(
        engine, [(0.002 * i, r) for i, r in enumerate(_requests(8, seed=11))]
    )
    assert len(results) == 8
    return engine, tracer


# -- reservoir / registry units ------------------------------------------


def test_reservoir_bounded_with_exact_aggregates():
    r = Reservoir(cap=64, seed=3)
    vals = np.random.default_rng(0).exponential(1.0, 10_000)
    for v in vals:
        r.add(v)
    assert len(r.values) == 64          # sample stays at cap
    assert r.n == 10_000                # aggregates stay exact
    assert r.total == pytest.approx(vals.sum())
    assert r.min == pytest.approx(vals.min())
    assert r.max == pytest.approx(vals.max())
    assert r.mean == pytest.approx(vals.mean())
    # the sample is drawn from the series, not fabricated
    pool = set(np.round(vals, 12))
    assert all(round(v, 12) in pool for v in r.values)
    with pytest.raises(ValueError):
        Reservoir(cap=0)


def test_registry_validation():
    reg = MetricsRegistry()
    with pytest.raises(ValueError):
        reg.counter("bad name")
    with pytest.raises(ValueError):
        reg.counter("ok_name", labelnames=("bad-label",))
    c = reg.counter("requests_total", "help", labelnames=("outcome",))
    with pytest.raises(ValueError):
        reg.gauge("requests_total")     # kind mismatch on existing name
    assert reg.counter("requests_total") is c  # get-or-create
    with pytest.raises(ValueError):
        c.inc(outcome="x", extra="y")   # undeclared label
    with pytest.raises(ValueError):
        c.inc(-1, outcome="x")          # counters only go up
    with pytest.raises(ValueError):
        reg.gauge("g", labelnames=("a",)).set_function(lambda: 1)


def test_histogram_render_cumulative():
    reg = MetricsRegistry()
    h = reg.histogram("lat_seconds", "help", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.05, 0.5, 5.0):
        h.observe(v)
    text = reg.render()
    assert 'lat_seconds_bucket{le="0.01"} 1' in text
    assert 'lat_seconds_bucket{le="0.1"} 3' in text
    assert 'lat_seconds_bucket{le="1"} 4' in text
    assert 'lat_seconds_bucket{le="+Inf"} 5' in text
    assert "lat_seconds_count 5" in text
    assert h.count() == 5
    m = re.search(r"lat_seconds_sum (\S+)", text)
    assert float(m.group(1)) == pytest.approx(5.605)


# -- tracer --------------------------------------------------------------


def test_disabled_tracer_records_nothing(traced_run):
    """The default engine tracer is disabled and must buffer ZERO
    events across a full serving run — the overhead guard."""
    engine = ServingEngine(CFG, _params(), n_slots=2, temperature=0.0)
    assert not engine.tracer.enabled
    run_request_trace(
        engine, [(0.0, r) for r in _requests(3, seed=5)]
    )
    assert engine.tracer.n_events == 0
    assert engine.tracer.dropped == 0
    # region() must not take timestamps either
    with engine.tracer.region("t", "x"):
        pass
    assert engine.tracer.n_events == 0


def test_tracer_ring_buffer_bounds_memory():
    t = Tracer(enabled=True, capacity=8)
    for i in range(100):
        t.span("trk", "s", float(i), 0.5)
    assert t.n_events == 8
    assert t.dropped == 92
    # oldest events were the ones overwritten
    spans = [e for e in t.chrome_trace()["traceEvents"] if e["ph"] == "X"]
    assert len(spans) == 8


def test_chrome_trace_export_is_valid(traced_run, tmp_path):
    """Structural validation of the exported Chrome-trace JSON: it
    json-round-trips, declares its tracks via metadata events, spans
    carry non-negative µs ts/dur, and per-track spans NEST (no partial
    overlap) on the engine and slot tracks. The scheduler track is
    exempt from the nesting check: concurrent requests legitimately
    overlap their ``queued`` spans."""
    engine, tracer = traced_run
    path = tracer.export(tmp_path / "trace.json")
    doc = json.loads(path.read_text())

    assert doc["displayTimeUnit"] == "ms"
    evs = doc["traceEvents"]
    meta = [e for e in evs if e["ph"] == "M"]
    names = {e["args"]["name"] for e in meta if e["name"] == "thread_name"}
    assert ENGINE_TRACK in names and SCHEDULER_TRACK in names
    assert any(n.startswith("slot-") for n in names)
    sort_idx = {e["tid"] for e in meta if e["name"] == "thread_sort_index"}
    named = {e["tid"] for e in meta if e["name"] == "thread_name"}
    assert sort_idx == named  # every track is both named and ordered
    tid_name = {
        e["tid"]: e["args"]["name"] for e in meta
        if e["name"] == "thread_name"
    }

    spans = [e for e in evs if e["ph"] == "X"]
    assert spans, "a traced serving run must produce spans"
    span_names = {e["name"] for e in spans}
    for expected in ("queued", "prefill", "decode", "dispatch", "sync",
                     "step"):
        assert expected in span_names, f"missing lifecycle span {expected}"
    for e in spans:
        assert e["pid"] == 1
        assert e["ts"] >= 0 and e["dur"] >= 0
    for e in evs:
        if e["ph"] == "i":
            assert e["s"] == "t"
    # request ids correlate spans with logs/metrics
    assert any(
        "req_id" in (e.get("args") or {}) for e in spans
    )

    # nesting check (stack of end-times) per engine/slot track
    eps = 0.5  # µs slack for the 3-decimal rounding in the exporter
    by_tid = {}
    for e in spans:
        by_tid.setdefault(e["tid"], []).append(e)
    checked = 0
    for tid, track_spans in by_tid.items():
        name = tid_name[tid]
        if not (name == ENGINE_TRACK or name.startswith("slot-")):
            continue
        checked += 1
        stack = []  # end timestamps of open spans
        for e in sorted(track_spans, key=lambda e: (e["ts"], -e["dur"])):
            start, end = e["ts"], e["ts"] + e["dur"]
            while stack and stack[-1] <= start + eps:
                stack.pop()
            if stack:
                assert end <= stack[-1] + eps, (
                    f"span {e['name']!r} on {name} overlaps its "
                    f"enclosing span partially"
                )
            stack.append(end)
    assert checked >= 2  # engine + at least one slot track


def test_trace_counters_and_clear(traced_run):
    engine, tracer = traced_run
    evs = tracer.chrome_trace()["traceEvents"]
    counters = [e for e in evs if e["ph"] == "C"]
    assert {"queue_depth", "kv_slots_active"} <= {e["name"] for e in counters}
    for e in counters:
        (k, v), = e["args"].items()
        assert isinstance(v, float)
    t = Tracer(enabled=True, capacity=4)
    t.instant("x", "y")
    t.clear()
    assert t.n_events == 0 and t.dropped == 0


# -- serving metrics: prometheus + phase breakdown -----------------------

_PROM_LINE = re.compile(
    r"^(?:# (?:HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .*"
    r"|[a-zA-Z_:][a-zA-Z0-9_:]*(?:\{[^}]*\})? (?:[-+0-9.eE]+|\+Inf|NaN))$"
)

#: metric families the serving dashboards scrape
_REQUIRED_FAMILIES = (
    "serve_requests_total",
    "serve_tokens_generated_total",
    "serve_engine_steps_total",
    "serve_retries_total",
    "serve_restarts_total",
    "serve_backpressure_total",
    "serve_queue_depth",
    "serve_kv_slots",
    "serve_kv_slots_active",
    "serve_kv_occupancy",
    "serve_kv_cache_bytes",
    "serve_ttft_seconds",
    "serve_tpot_seconds",
    "serve_phase_seconds",
)


def test_prometheus_text_parses_and_is_complete(traced_run):
    engine, _ = traced_run
    text = engine.metrics.render_prometheus()
    for line in text.strip().splitlines():
        assert _PROM_LINE.match(line), f"unparseable exposition line {line!r}"
    for fam in _REQUIRED_FAMILIES:
        assert f"# TYPE {fam} " in text, f"missing family {fam}"
    assert 'serve_requests_total{outcome="finished"} 8' in text
    assert "# TYPE serve_ttft_seconds histogram" in text
    assert 'serve_phase_seconds_bucket{phase="decode",le="+Inf"}' in text
    # histogram invariants: cumulative buckets are monotone, +Inf==count
    for fam in ("serve_ttft_seconds", "serve_tpot_seconds"):
        cum = [
            int(m.group(1)) for m in re.finditer(
                rf'{fam}_bucket{{le="[^"]+"}} (\d+)', text
            )
        ]
        assert cum == sorted(cum) and cum
        count = int(re.search(rf"{fam}_count (\d+)", text).group(1))
        assert cum[-1] == count


def test_phase_breakdown_in_summary(traced_run):
    engine, _ = traced_run
    s = engine.metrics.summary()
    assert set(s["phase_seconds"]) == set(PHASES)
    assert set(s["phase_frac"]) == set(PHASES)
    assert s["phase_seconds"]["decode"] > 0
    assert s["phase_seconds"]["prefill"] > 0
    for v in s["phase_frac"].values():
        assert 0.0 <= v <= 1.0
    # fractions are shares of ATTRIBUTED time; they sum to ~1
    assert sum(s["phase_frac"].values()) == pytest.approx(1.0, abs=0.01)
    assert s["decode_horizon"] == 2


def test_metrics_reservoirs_are_bounded():
    m = ServingMetrics(reservoir_cap=16)
    for i in range(1000):
        m.record_step(n_active=1, n_slots=2, queue_depth=i % 7)
    assert len(m.occupancy.values) == 16
    assert m.occupancy.n == 1000
    assert m.queue_depth.max == 6
    assert not math.isinf(m.queue_depth.min)


# -- profiling trigger ---------------------------------------------------


def test_profile_trigger_step_scoped_capture(tmp_path):
    trig = ProfileTrigger(log_dir=tmp_path)
    assert not trig.armed
    d = trig.arm(2)
    assert trig.armed
    with pytest.raises(RuntimeError):  # one capture at a time
        trig.arm(1)
    for _ in range(3):
        trig.step_start()
        jax.block_until_ready(jax.numpy.ones(8) * 2)
        trig.step_end()
    assert not trig.armed
    assert trig.n_captures == 1
    assert d.exists() and any(d.rglob("*")), "no XLA capture written"
    # disarmed hooks are no-ops
    trig.step_start()
    trig.step_end()
    assert trig.n_captures == 1
    with pytest.raises(ValueError):
        trig.arm(0)


# -- structured logs -----------------------------------------------------


def test_json_logs_correlate_by_req_id():
    buf = StringIO()
    pkg = logging.getLogger("deeplearning4j_tpu")
    old_level = pkg.level
    handler = configure_json_logging(level=logging.DEBUG, stream=buf)
    try:
        engine = ServingEngine(CFG, _params(), n_slots=2, temperature=0.0)
        for r in _requests(3, seed=9):
            engine.submit(r)
        engine.run()
    finally:
        pkg.removeHandler(handler)
        pkg.setLevel(old_level)
    lines = [ln for ln in buf.getvalue().splitlines() if ln.strip()]
    assert lines, "a logged serving run must emit records"
    recs = [json.loads(ln) for ln in lines]  # every line is one JSON obj
    for r in recs:
        assert {"ts", "level", "logger", "event"} <= set(r)
    by_req = {}
    for r in recs:
        if "req_id" in r:
            by_req.setdefault(r["req_id"], set()).add(r["event"])
    assert len(by_req) == 3
    for events in by_req.values():  # submit->admit->retire, one req_id
        assert {"request_admitted", "request_retired"} <= events


# -- training spans ------------------------------------------------------


def test_training_orchestrator_spans():
    from deeplearning4j_tpu.datasets import ListDataSetIterator, fetchers
    from deeplearning4j_tpu.models import MultiLayerNetwork
    from deeplearning4j_tpu.models.multilayer import TRAIN_TRACK
    from deeplearning4j_tpu.nn import conf as C

    base = C.LayerConfig(
        activation="tanh", lr=0.1, num_iterations=2,
        optimization_algo=C.OptimizationAlgorithm.GRADIENT_DESCENT,
    )
    mc = C.list_builder(base, sizes=[6], n_in=4, n_out=3,
                        hidden_layer_type="dense")
    mc.pretrain = False
    mc.backward = True
    tracer = Tracer(enabled=True)
    net = MultiLayerNetwork(mc, seed=1, tracer=tracer)
    net.init()
    ds = fetchers.iris().normalize_zero_mean_unit_variance()
    net.fit(ListDataSetIterator(ds, 150))

    evs = tracer.chrome_trace()["traceEvents"]
    spans = [e for e in evs if e["ph"] == "X"]
    assert all(e["cat"] == TRAIN_TRACK for e in spans)
    names = {e["name"] for e in spans}
    assert {"fit", "finetune", "finetune_batch"} <= names
    # fit encloses everything else on the track
    fit = next(e for e in spans if e["name"] == "fit")
    for e in spans:
        assert e["ts"] >= fit["ts"] - 0.5
        assert e["ts"] + e["dur"] <= fit["ts"] + fit["dur"] + 0.5

    # default-constructed network: tracing off, zero events
    net2 = MultiLayerNetwork(mc, seed=1)
    assert not net2.tracer.enabled


# -- server endpoints ----------------------------------------------------


def test_server_metrics_sidecar_and_profile_endpoint(tmp_path):
    import urllib.error
    import urllib.request

    engine = ServingEngine(
        CFG, _params(), n_slots=2, temperature=0.0,
        profile=ProfileTrigger(log_dir=tmp_path),
    )
    srv = ServingServer(engine, port=0, metrics_port=0).start()

    def get(base, path):
        with urllib.request.urlopen(f"{base}{path}", timeout=10) as r:
            return r.status, r.headers.get("Content-Type"), r.read().decode()

    def post(base, path):
        try:
            with urllib.request.urlopen(
                urllib.request.Request(f"{base}{path}", data=b""),
                timeout=10,
            ) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    try:
        host, port = srv.address
        mhost, mport = srv.metrics_address
        assert mport != port
        main = f"http://{host}:{port}"
        side = f"http://{mhost}:{mport}"

        # the sidecar serves the same scrape surface as the main port
        for base in (main, side):
            code, ctype, text = get(base, "/metrics")
            assert code == 200 and "version=0.0.4" in ctype
            assert "# TYPE serve_queue_depth gauge" in text
            assert "serve_engine_alive 1" in text
            assert "serve_draining 0" in text
        code, _, text = get(side, "/healthz")
        assert code == 200

        code, body = post(main, "/profile?s=2")
        assert code == 200 and body["armed"] == 2
        code, body = post(main, "/profile?s=1")
        assert code == 409  # already armed
        code, body = post(main, "/profile?s=0")
        assert code == 400
    finally:
        srv.stop()

    # a server whose engine has no trigger refuses politely
    engine2 = ServingEngine(CFG, _params(), n_slots=2, temperature=0.0)
    srv2 = ServingServer(engine2, port=0).start()
    try:
        host, port = srv2.address
        code, body = post(f"http://{host}:{port}", "/profile?s=1")
        assert code == 503
    finally:
        srv2.stop()
