"""Real-data parity gates consuming the reference's read-only test
fixtures (data-only use of the /root/reference mount — no code).

Every reference acceptance test runs on real data; these gates do the
same with the files that physically ship in the reference tree:

- ``iris.dat``                  ≙ MultiLayerTest.java:79-116 (DBN/MLP on Iris)
- ``big/raw_sentences.txt``     ≙ Word2VecTests.java (similarity bound on a
                                  real corpus; the 97k-sentence fixture)
- ``vec.bin`` / ``vec.txt``     ≙ WordVectorSerializer.loadGoogleModel:42
                                  (real Google-format files, both codecs)
- ``reuters/``                  ≙ the nlp text-pipeline fixtures
- t-SNE runs on the real iris features (the reference's mnist2500_X.txt
  fixture does NOT exist in this snapshot — only mnist2500_labels.txt —
  so the t-SNE gate uses the other real fixture)

All tests skip cleanly when the reference mount is absent.

Triage note (ADVICE r4): the gates marked ``statistical`` below assert
thresholds on seeded-but-platform-sensitive training runs. A jaxlib /
hardware / RNG-implementation change can move the measured value with no
repo bug; when one of these fails in isolation, compare against the
stamp-time margin recorded at the assert site and triage as environment
drift BEFORE suspecting a code regression.
"""

import numpy as np
import pytest

REF = "/root/reference"
NLP_RES = f"{REF}/deeplearning4j-scaleout/deeplearning4j-nlp/src/test/resources"
CORE_RES = f"{REF}/deeplearning4j-core/src/test/resources"


def _need(path):
    import os

    if not os.path.exists(path):
        pytest.skip(f"reference fixture {path} not present")
    return path


def _load_reference_iris():
    rows = [
        line.strip().split(",")
        for line in open(_need(f"{CORE_RES}/iris.dat"))
        if line.strip()
    ]
    x = np.array([[float(v) for v in r[:4]] for r in rows], np.float32)
    y = np.array([int(r[4]) for r in rows])
    return x, y


def test_mlp_on_reference_iris_dat():
    """Train on the actual iris.dat the reference acceptance test uses
    (150 rows, 3 classes) and require real learning."""
    from deeplearning4j_tpu.datasets.base import DataSet, to_one_hot
    from deeplearning4j_tpu.evaluation import Evaluation
    from deeplearning4j_tpu.models import MultiLayerNetwork
    from deeplearning4j_tpu.nn import conf as C

    x, y = _load_reference_iris()
    assert x.shape == (150, 4) and set(np.bincount(y)) == {50}
    ds = DataSet(x, to_one_hot(y, 3)).shuffle(123)
    ds = ds.normalize_zero_mean_unit_variance()
    train, test = ds.split_test_and_train(110)
    base = C.LayerConfig(
        activation="tanh", lr=0.1, num_iterations=200,
        optimization_algo=C.OptimizationAlgorithm.CONJUGATE_GRADIENT,
        use_adagrad=True, momentum=0.5, weight_init="vi",
    )
    mc = C.list_builder(base, sizes=[8], n_in=4, n_out=3)
    mc.pretrain = False
    mc.backward = True
    net = MultiLayerNetwork(mc, seed=42)
    net.init()
    net.fit_dataset(train)
    ev = Evaluation(3)
    ev.eval(test.labels, np.asarray(net.output(test.features)))
    assert ev.accuracy() > 0.85, ev.stats()


@pytest.mark.slow
def test_word2vec_real_corpus_similarity_bound():
    """Train on the real raw_sentences.txt corpus and assert the
    similarity("day","night") bound ≙ Word2VecTests.java — the corpus
    where that classic assertion comes from (97k sentences; a 20k
    subsample keeps the gate under ~15s while converging)."""
    from deeplearning4j_tpu.models.word2vec import Word2Vec
    from deeplearning4j_tpu.nlp.sentence_iterator import (
        CollectionSentenceIterator,
    )

    path = _need(f"{NLP_RES}/big/raw_sentences.txt")
    lines = [ln.strip().lower() for ln in open(path) if ln.strip()]
    assert len(lines) > 90_000  # the real fixture, not a stub
    sub = lines[:20_000]
    w2v = Word2Vec(
        layer_size=50, window=5, min_word_frequency=5, epochs=2,
        sample=1e-3, seed=7,
    )
    w2v.fit(CollectionSentenceIterator(sub))
    sim = w2v.similarity("day", "night")
    # statistical gate — stamp-time margin (2026-07-31, jax 0.9.0 CPU):
    # measured sim 0.909 vs the 0.65 bound; see module triage note
    assert sim > 0.65, sim
    # and the bound is meaningful: an unrelated pair scores clearly lower
    # (stamp-time: 0.909 vs 0.697 + 0.1)
    assert sim > w2v.similarity("day", "office") + 0.1


def test_load_google_model_real_bin_and_txt():
    """read_binary against the actual Google-format vec.bin shipped in
    the reference (≙ WordVectorSerializer.loadGoogleModel:42), cross-
    checked against its text twin vec.txt."""
    from deeplearning4j_tpu.nlp.serializer import read_binary, read_text

    wb, vb = read_binary(_need(f"{NLP_RES}/vec.bin"))
    wt, vt = read_text(_need(f"{NLP_RES}/vec.txt"))
    assert wb == wt == ["</s>", "Adam", "is", "awesome."]
    assert vb.shape == vt.shape == (4, 100)
    # same model, two codecs: txt rounds to 6 decimals
    assert np.max(np.abs(vb - vt)) < 1e-5


@pytest.mark.slow
def test_tsne_on_reference_iris_preserves_classes():
    """t-SNE on the real iris.dat features: the 2-D embedding keeps
    same-class points as nearest neighbours (the reference's TsneTest
    only smoke-runs; this asserts structure)."""
    from deeplearning4j_tpu.plot.tsne import Tsne

    x, y = _load_reference_iris()
    emb = Tsne(perplexity=20, n_iter=300, seed=0).calculate(x)
    assert emb.shape == (150, 2)
    d = ((emb[:, None, :] - emb[None, :, :]) ** 2).sum(-1)
    np.fill_diagonal(d, np.inf)
    agreement = (y[d.argmin(1)] == y).mean()
    # statistical gate — stamp-time margin (2026-07-31, jax 0.9.0 CPU):
    # measured agreement 0.967 vs the 0.9 bound; see module triage note
    assert agreement > 0.9, agreement


@pytest.mark.slow
def test_glove_on_real_cooccurrence_fixture():
    """GloVe's AdaGrad WLS trained directly on the reference's real
    co-occurrence dump big/coc.txt (the artifact CoOccurrences.fit
    produces, ≙ Glove.doIteration:151 consuming it): loss falls and the
    learned factorization w_i·wc_j + b_i + bc_j actually tracks
    log X_ij."""
    from deeplearning4j_tpu.models.glove import Glove

    path = _need(f"{NLP_RES}/big/coc.txt")
    triples = []
    for ln in open(path):
        parts = ln.split()
        if len(parts) == 3:
            triples.append((parts[0], parts[1], float(parts[2])))
    assert len(triples) > 20_000  # the real 26k-line fixture
    g = Glove(layer_size=32, epochs=8, lr=0.05, batch=4096, seed=3)
    g.fit_cooccurrences(triples)
    assert g.loss_history[-1] < g.loss_history[0] / 2, g.loss_history
    # the factorization explains the data: predicted log-counts
    # correlate strongly with the fixture's actual log-counts
    w = np.asarray(g.w)
    wc = np.asarray(g.wc)
    b = np.asarray(g.b)
    bc = np.asarray(g.bc)
    idx = np.random.default_rng(0).choice(len(triples), 4000, replace=False)
    pred, logx = [], []
    for k in idx:
        w1, w2, x = triples[k]
        i, j = g.cache.index_of(w1), g.cache.index_of(w2)
        pred.append(w[i] @ wc[j] + b[i] + bc[j])
        logx.append(np.log(x))
    corr = np.corrcoef(pred, logx)[0, 1]
    assert corr > 0.5, corr


def test_tfidf_on_real_reuters_docs():
    """BoW/TF-IDF over the real Reuters articles in the reference tree:
    content words outrank stop words, and a doc-specific term stays
    specific to its document."""
    import os

    from deeplearning4j_tpu.nlp.vectorizers import TfidfVectorizer

    root = _need(f"{NLP_RES}/reuters")
    texts = []
    for name in sorted(os.listdir(root)):
        with open(os.path.join(root, name), errors="replace") as f:
            texts.append(f.read().lower())
    assert len(texts) >= 3
    tfidf = TfidfVectorizer().fit(texts)
    m = tfidf.transform(texts)
    assert m.shape[0] == len(texts)
    # 'pearson' is the subject of doc 5250 only; 'said' is everywhere
    pearson = tfidf.cache.index_of("pearson")
    said = tfidf.cache.index_of("said")
    assert pearson >= 0 and said >= 0
    assert m[0, pearson] > m[0, said]
    # and it does not leak into the other documents
    assert m[0, pearson] > m[1, pearson] and m[0, pearson] > m[2, pearson]
