"""Long-context tests: online-softmax math, ring attention == dense
attention on the 8-device mesh, sequence-sharded LSTM == single-device."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn import conf as C
from deeplearning4j_tpu.nn import layers as L
from deeplearning4j_tpu.ops.attention import attention, blocked_attention
from deeplearning4j_tpu.parallel import data_parallel_mesh
from deeplearning4j_tpu.parallel.sequence_parallel import (
    ring_attention,
    sequence_sharded_lstm,
)


def _qkv(b=2, t=32, h=2, d=8, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    return tuple(jax.random.normal(k, (b, t, h, d)) for k in ks)


def test_blocked_attention_matches_dense():
    q, k, v = _qkv()
    dense = attention(q, k, v)
    blocked = blocked_attention(q, k, v, block_size=8)
    assert jnp.max(jnp.abs(dense - blocked)) < 1e-4


def test_blocked_attention_causal_matches_dense():
    q, k, v = _qkv(seed=1)
    dense = attention(q, k, v, causal=True)
    blocked = blocked_attention(q, k, v, block_size=8, causal=True)
    assert jnp.max(jnp.abs(dense - blocked)) < 1e-4


def test_ring_attention_matches_dense(devices):
    mesh = data_parallel_mesh(8)
    q, k, v = _qkv(t=64, seed=2)
    ring = ring_attention(mesh)
    out = ring(q, k, v)
    dense = attention(q, k, v)
    assert jnp.max(jnp.abs(out - dense)) < 1e-4


def test_ring_attention_causal_matches_dense(devices):
    mesh = data_parallel_mesh(8)
    q, k, v = _qkv(t=64, seed=3)
    ring = ring_attention(mesh, causal=True)
    out = ring(q, k, v)
    dense = attention(q, k, v, causal=True)
    assert jnp.max(jnp.abs(out - dense)) < 1e-4


def test_sequence_sharded_lstm_matches_single_device(devices):
    mesh = data_parallel_mesh(8)
    v = 8
    cfg = C.LayerConfig(layer_type="lstm", n_in=v, n_out=v, activation="tanh")
    mod = L.get("lstm")
    params = mod.init(jax.random.key(0), cfg)
    x = jax.random.normal(jax.random.key(1), (2, 32, v))  # T=32 over 8 devs
    hs_ref, cs_ref = mod.scan_hidden(params, cfg, x)
    fn = sequence_sharded_lstm(mesh, mod, cfg)
    hs, cs = fn(params, x)
    assert jnp.max(jnp.abs(hs - hs_ref)) < 1e-4
    assert jnp.max(jnp.abs(cs - cs_ref)) < 1e-4
