"""Tensor-parallel block parity, remat trainer, mixed-precision policy,
and the full driver dryrun entry."""

import pytest
import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu import dtypes
from deeplearning4j_tpu.parallel import DataParallelTrainer, data_parallel_mesh
from deeplearning4j_tpu.parallel.mesh import dp_mp_mesh
from deeplearning4j_tpu.parallel.tensor_parallel import shard_dense_params, tp_mlp_block


def test_tp_mlp_block_matches_dense(devices):
    mesh = dp_mp_mesh(4, 2)
    rng = np.random.default_rng(0)
    d_in, hidden, d_out = 6, 8, 5
    w1 = jnp.asarray(rng.normal(size=(d_in, hidden)).astype(np.float32))
    b1 = jnp.asarray(rng.normal(size=(hidden,)).astype(np.float32))
    w2 = jnp.asarray(rng.normal(size=(hidden, d_out)).astype(np.float32))
    b2 = jnp.asarray(rng.normal(size=(d_out,)).astype(np.float32))
    x = jnp.asarray(rng.normal(size=(3, d_in)).astype(np.float32))
    block = tp_mlp_block(mesh)
    y = block(x, *shard_dense_params(mesh, w1, b1, w2, b2))
    ref = jnp.tanh(x @ w1 + b1) @ w2 + b2
    assert jnp.max(jnp.abs(y - ref)) < 1e-4


def test_remat_trainer_matches_plain(devices):
    from deeplearning4j_tpu.models import MultiLayerNetwork
    from deeplearning4j_tpu.nn import conf as C

    mc = C.list_builder(
        C.LayerConfig(activation="tanh"), sizes=[16], n_in=8, n_out=3,
        pretrain=False, backward=True,
    )
    net = MultiLayerNetwork(mc, seed=0)
    params = net.init()

    def loss(p, x, y, key=None):
        return net.supervised_score_fn(p, x, y)

    import optax

    mesh = data_parallel_mesh(8)
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(64, 8)).astype(np.float32))
    y = jnp.asarray(np.eye(3, dtype=np.float32)[rng.integers(0, 3, 64)])
    t_plain = DataParallelTrainer(loss, mesh=mesh, optimizer=optax.sgd(0.1))
    t_remat = DataParallelTrainer(loss, mesh=mesh, optimizer=optax.sgd(0.1), remat=True)
    s1, s2 = t_plain.init(params), t_remat.init(params)
    for i in range(3):
        s1, l1 = t_plain.step(s1, *t_plain.shard_batch(x, y), jax.random.key(i))
        s2, l2 = t_remat.step(s2, *t_remat.shard_batch(x, y), jax.random.key(i))
    assert abs(float(l1) - float(l2)) < 1e-5


def test_mixed_bf16_policy_forward():
    from deeplearning4j_tpu.models.lenet import build_lenet

    with dtypes.policy(dtypes.MIXED_BF16):
        net, params = build_lenet(seed=0)
        # params stay f32; compute casts to bf16
        assert params[0]["convweights"].dtype == jnp.float32
        out = net.feed_forward_fn(params, jnp.zeros((4, 784)))[-1]
    assert out.dtype in (jnp.bfloat16, jnp.float32)
    assert np.isfinite(np.asarray(out, np.float32)).all()


def test_mixed_bf16_loss_runs_in_accum_dtype():
    # softmax/log/loss must run f32 under MIXED_BF16 — bf16
    # log-probabilities stall training on deeper nets (seen on AlexNet)
    from deeplearning4j_tpu.models import MultiLayerNetwork
    from deeplearning4j_tpu.nn import conf as C

    with dtypes.policy(dtypes.MIXED_BF16):
        mc = C.list_builder(
            C.LayerConfig(activation="relu"), sizes=[16], n_in=8, n_out=3,
            pretrain=False, backward=True,
        )
        net = MultiLayerNetwork(mc, seed=0)
        params = net.init()
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(16, 8)).astype(np.float32))
        y = jnp.asarray(np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)])
        score = net.supervised_score_fn(params, x, y)
        assert score.dtype == jnp.float32
        # training still converges under the mixed policy
        trainer = DataParallelTrainer(
            lambda p, xx, yy, key=None: net.supervised_score_fn(p, xx, yy),
            mesh=data_parallel_mesh(8),
        )
        state = trainer.init(params)
        xs, ys = trainer.shard_batch(x, y)
        state, losses = trainer.run_steps(state, xs, ys, jax.random.key(0), 60)
        l = np.asarray(losses)
        assert np.isfinite(l).all() and l[-1] < l[0] * 0.5


@pytest.mark.slow
def test_graft_dryrun_multichip(devices):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "graft_entry_test", "/root/repo/__graft_entry__.py"
    )
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    fn, args = m.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (8, 10)
    m.dryrun_multichip(8)
