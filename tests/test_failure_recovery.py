"""End-to-end failure recovery: kill a training process mid-run, restart
from the latest checkpoint, and prove the final model matches an
uninterrupted run — with master-side eviction of the dead worker.

≙ the reference's supervision story (MasterActor.java:99-153: worker
eviction on silent heartbeats + job re-queue; ModelSavingActor periodic
saves making the restart possible). The resume-cadence contract:
checkpoints are atomic (write-to-temp + rename), saved every
``save_every`` steps with ``step`` recorded in the manifest; a restart
replays from the last saved step, so with step-indexed data and a
stateless optimizer the recovered run is numerically identical to an
uninterrupted one. At most ``save_every`` steps of work are ever lost.
"""

import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent
WORKER = Path(__file__).resolve().parent / "recovery_worker.py"

TOTAL, EVERY, KILL_AT = 40, 5, 20


def _spawn(ckpt_dir, status_url=None, final=None, step_delay=0.0):
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [sys.executable, str(WORKER), str(ckpt_dir), str(TOTAL), str(EVERY)]
    if status_url:
        cmd += ["--status-url", status_url]
    if final:
        cmd += ["--final", str(final)]
    if step_delay:
        cmd += ["--step-delay", str(step_delay)]
    return subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=str(REPO),
    )


@pytest.mark.slow
def test_kill_restart_resumes_losslessly(tmp_path):
    from deeplearning4j_tpu.parallel.cluster import ClusterService

    # master blackboard with a short eviction window; the worker
    # heartbeats over REST (≙ WorkerActor.heartbeat -> MasterActor sweep)
    svc = ClusterService(evict_after=2.0)
    port = svc.start_rest_api(0)
    status_url = f"http://127.0.0.1:{port}"

    ckpt = tmp_path / "ckpt"

    # run 1: train (throttled so the parent has a window) until a
    # checkpoint at step >= KILL_AT exists, then SIGKILL. Retention
    # (keep=3) garbage-collects old files, so poll the latest step, not
    # one specific filename.
    import re as _re

    def latest_step():
        steps = [
            int(m.group(1))
            for f in ckpt.glob("ckpt_*.npz")
            if (m := _re.search(r"ckpt_(\d+)\.npz$", f.name))
        ]
        return max(steps, default=-1)

    p1 = _spawn(ckpt, status_url=status_url, step_delay=0.15)
    deadline = time.monotonic() + 180
    while latest_step() < KILL_AT:
        assert time.monotonic() < deadline, "checkpoint never appeared"
        assert p1.poll() is None, f"worker exited early:\n{p1.stdout.read()}"
        time.sleep(0.05)
    p1.send_signal(signal.SIGKILL)
    p1.wait(timeout=30)
    assert p1.returncode != 0  # it was killed, not finished

    # the worker had registered via heartbeats...
    assert svc.workers() == ["w0"]
    # ...and goes silent -> the master's sweep evicts it
    time.sleep(2.2)
    assert svc.evict_stale() == ["w0"]
    assert svc.workers() == []
    svc.stop_rest_api()

    # run 2: restart against the same checkpoint dir -> resumes and finishes
    final_rec = tmp_path / "final_recovered.npz"
    p2 = _spawn(ckpt, final=final_rec)
    out2, _ = p2.communicate(timeout=300)
    assert p2.returncode == 0, out2[-3000:]
    resumed = [ln for ln in out2.splitlines() if ln.startswith("RESUMED_FROM=")]
    assert resumed, out2[-2000:]
    resumed_step = int(resumed[0].split("=")[1])
    assert resumed_step >= KILL_AT  # restart lost at most save_every steps
    assert resumed_step < TOTAL

    # reference: one uninterrupted run
    ref_dir = tmp_path / "ckpt_ref"
    final_ref = tmp_path / "final_ref.npz"
    p3 = _spawn(ref_dir, final=final_ref)
    out3, _ = p3.communicate(timeout=300)
    assert p3.returncode == 0, out3[-3000:]

    # recovered == uninterrupted, leaf by leaf
    with np.load(final_rec) as a, np.load(final_ref) as b:
        assert sorted(a.files) == sorted(b.files)
        for k in a.files:
            np.testing.assert_allclose(
                a[k], b[k], rtol=0, atol=0,
                err_msg=f"leaf {k} diverged after recovery",
            )
