"""NLP-stack tests ≙ reference Word2VecTests (similarity bounds),
tokenizer tests, TF-IDF tests, Huffman correctness."""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import serializer
from deeplearning4j_tpu.nlp.inverted_index import InvertedIndex
from deeplearning4j_tpu.nlp.sentence_iterator import (
    CollectionSentenceIterator,
    LabelAwareSentenceIterator,
    LineSentenceIterator,
)
from deeplearning4j_tpu.nlp.stopwords import remove_stop_words
from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizer,
    NGramTokenizer,
    input_homogenization,
    split_sentences,
)
from deeplearning4j_tpu.nlp.vectorizers import BagOfWordsVectorizer, TfidfVectorizer, windows
from deeplearning4j_tpu.nlp.vocab import VocabCache
from deeplearning4j_tpu.models.glove import Glove, count_cooccurrences
from deeplearning4j_tpu.models.paragraph_vectors import ParagraphVectors
from deeplearning4j_tpu.models.word2vec import Word2Vec, skipgram_pairs


def _synthetic_corpus(n=300, seed=0):
    """Two topic clusters: day/sun/light/morning vs night/moon/dark/evening.

    Gives similarity structure a correct Word2Vec must recover
    (≙ Word2VecTests asserting similarity('day','night') bounds)."""
    rng = np.random.default_rng(seed)
    day = ["day", "sun", "light", "morning", "bright", "noon"]
    night = ["night", "moon", "dark", "evening", "stars", "midnight"]
    fillers = ["the", "a", "was", "very", "and", "it", "sky", "time"]
    sents = []
    for _ in range(n):
        topic = day if rng.random() < 0.5 else night
        words = list(rng.choice(topic, size=4)) + list(rng.choice(fillers, size=3))
        rng.shuffle(words)
        sents.append(" ".join(words))
    return sents


def test_tokenizer_and_homogenization():
    t = DefaultTokenizer()
    assert t.tokens("Hello, World! it's fine.") == ["hello", "world", "it's", "fine"]
    assert input_homogenization("Café, DÉJÀ-vu!") == "cafe  deja vu "
    ng = NGramTokenizer(DefaultTokenizer(), 1, 2)
    toks = ng.tokens("a b c")
    assert "a b" in toks and "b c" in toks and "a" in toks
    assert split_sentences("One. Two! Three?") == ["One.", "Two!", "Three?"]
    assert remove_stop_words(["the", "cat", "and", "dog"]) == ["cat", "dog"]


def test_sentence_iterators(tmp_path):
    ci = CollectionSentenceIterator(["a b", "c d"])
    assert list(ci) == ["a b", "c d"]
    p = tmp_path / "text.txt"
    p.write_text("line one\n\nline two\n")
    li = LineSentenceIterator(p)
    assert list(li) == ["line one", "line two"]

    root = tmp_path / "corpus"
    (root / "pos").mkdir(parents=True)
    (root / "neg").mkdir()
    (root / "pos" / "a.txt").write_text("Good stuff. Nice thing.")
    (root / "neg" / "b.txt").write_text("Bad stuff.")
    la = LabelAwareSentenceIterator(root)
    pairs = list(la)
    assert ("neg", "Bad stuff.") in pairs
    assert sum(1 for label, _ in pairs if label == "pos") == 2


def test_vocab_and_huffman():
    cache = VocabCache(min_word_frequency=1)
    cache.fit([["a", "a", "a", "b", "b", "c"]])
    cache.build_huffman()
    assert len(cache) == 3
    # most frequent word gets the shortest code
    assert len(cache.vocab["a"].codes) <= len(cache.vocab["c"].codes)
    codes, points, mask = cache.huffman_arrays()
    assert codes.shape == points.shape == mask.shape
    assert mask.sum() == sum(len(v.codes) for v in cache.vocab.values())
    # prefix-free: no word's code is another's prefix
    all_codes = ["".join(map(str, cache.vocab[w].codes)) for w in cache.words()]
    for i, a in enumerate(all_codes):
        for j, b in enumerate(all_codes):
            if i != j:
                assert not b.startswith(a)
    table = cache.unigram_table(size=1000)
    assert (np.bincount(table, minlength=3).argmax()) == cache.index_of("a")


def test_skipgram_pairs_window():
    rng = np.random.default_rng(0)
    ins, tgts = skipgram_pairs([1, 2, 3, 4], window=2, rng=rng)
    assert len(ins) == len(tgts) > 0
    assert set(ins) <= {1, 2, 3, 4}


def test_inverted_index():
    idx = InvertedIndex()
    idx.add_document(["a", "b"])
    idx.add_document(["b", "c"])
    assert idx.documents("b") == [0, 1]
    assert idx.doc_frequency("a") == 1
    assert idx.document(1) == ["b", "c"]


def test_inverted_index_persistence_labels_minibatches(tmp_path):
    """npz save/load round-trip + labels + sampled mini-batches
    (≙ LuceneInvertedIndex persistence :910, miniBatches/sample,
    documentWithLabels)."""
    idx = InvertedIndex(sample=0.0)
    idx.add_document(["a", "b"], labels=["pos"])
    idx.add_document(["b", "c"])
    idx.add_label_for_doc(1, "neg")
    idx.add_word_to_doc(1, "d")
    assert idx.document_with_labels(0) == (["a", "b"], ["pos"])
    assert idx.documents("d") == [1]

    path = str(tmp_path / "index.npz")
    idx.save(path)
    loaded = InvertedIndex.load(path)
    assert loaded.num_documents() == 2
    assert loaded.all_docs() == idx.all_docs()
    assert loaded.document_with_labels(1) == (["b", "c", "d"], ["neg"])
    assert loaded.documents("b") == [0, 1]

    # sample=0 -> every doc appears exactly once across mini-batches
    batches = list(loaded.mini_batches(1))
    assert [b[0] for b in batches] == loaded.all_docs()
    # sample<1 keeps a subset
    loaded.sample = 1e-9
    assert list(loaded.mini_batches(2, seed=1)) == []


def test_bow_and_tfidf():
    texts = ["the cat sat", "the dog sat", "the cat ran"]
    bow = BagOfWordsVectorizer().fit(texts)
    m = bow.transform(texts)
    assert m.shape == (3, len(bow.cache))
    assert m[0, bow.cache.index_of("cat")] == 1

    tfidf = TfidfVectorizer().fit(texts)
    t = tfidf.transform(texts)
    # 'the' appears everywhere -> lowest idf weight
    the_col = tfidf.cache.index_of("the")
    cat_col = tfidf.cache.index_of("cat")
    assert t[0, the_col] < t[0, cat_col]

    w = windows(["a", "b", "c"], window_size=3)
    assert len(w) == 3 and w[0] == ["<NONE>", "a", "b"]


def test_word2vec_learns_topic_similarity():
    """≙ Word2VecTests.testRunWord2Vec similarity assertions."""
    sents = _synthetic_corpus(400)
    # epochs retuned after the saturated-dot skip fix (reference
    # parity): converged separation needs more passes on this tiny corpus
    w2v = Word2Vec(layer_size=32, window=5, epochs=24, lr=0.05, seed=1)
    w2v.fit(CollectionSentenceIterator(sents))
    sim_same = w2v.similarity("day", "sun")
    sim_cross = w2v.similarity("day", "moon")
    assert sim_same > sim_cross, (sim_same, sim_cross)
    near = w2v.words_nearest("night", top=5)
    night_topic = {"moon", "dark", "evening", "stars", "midnight"}
    assert len(night_topic & set(near)) >= 2, near


def test_word2vec_negative_sampling_path():
    sents = _synthetic_corpus(200)
    w2v = Word2Vec(
        layer_size=16, window=3, epochs=4, lr=0.05,
        use_hierarchical_softmax=False, negative=5, seed=2,
    )
    w2v.fit(CollectionSentenceIterator(sents))
    assert np.isfinite(np.asarray(w2v.syn0)).all()
    assert w2v.similarity("day", "sun") > w2v.similarity("day", "midnight")


def test_word2vec_distributed_matches_semantics(devices):
    """Sharded-delta-average path (≙ Word2VecPerformer/JobAggregator)."""
    from deeplearning4j_tpu.parallel import data_parallel_mesh

    sents = _synthetic_corpus(200)
    w2v = Word2Vec(layer_size=16, window=3, epochs=4, lr=0.05, seed=3, batch_pairs=1024)
    w2v.build_vocab(CollectionSentenceIterator(sents))
    w2v.reset_weights()
    w2v.fit_distributed(CollectionSentenceIterator(sents), mesh=data_parallel_mesh(8))
    assert np.isfinite(np.asarray(w2v.syn0)).all()
    assert np.abs(np.asarray(w2v.syn0)).max() > 1e-4  # actually trained


def test_serializer_roundtrips(tmp_path):
    words = ["alpha", "beta"]
    vecs = np.array([[1.0, 2.0, 3.0], [4.0, 5.0, 6.0]], dtype=np.float32)
    serializer.write_text(tmp_path / "v.txt", words, vecs)
    w2, v2 = serializer.read_text(tmp_path / "v.txt")
    assert w2 == words and np.allclose(v2, vecs, atol=1e-5)

    serializer.write_binary(tmp_path / "v.bin", words, vecs)
    w3, v3 = serializer.read_binary(tmp_path / "v.bin")
    assert w3 == words and np.allclose(v3, vecs)

    m = serializer.load_into_word2vec(Word2Vec, words, vecs)
    assert np.allclose(m.get_word_vector("beta"), [4, 5, 6])


def test_glove_learns_cooccurrence_structure():
    rows, cols, vals = count_cooccurrences([[0, 1, 2], [0, 1]], window=2)
    assert len(rows) > 0
    g = Glove(layer_size=16, window=4, epochs=30, lr=0.05, batch=512, seed=4)
    g.fit(CollectionSentenceIterator(_synthetic_corpus(200)))
    assert g.loss_history[-1] < g.loss_history[0]
    assert g.similarity("day", "sun") > g.similarity("day", "moon")


def test_glove_fit_cooccurrences_preserves_prebuilt_vocab():
    """ADVICE r4: fit_cooccurrences after fit() must reuse the existing
    vocab (same guard as fit()) and continue training instead of
    silently resetting weights; OOV triple words are dropped."""
    g = Glove(layer_size=8, window=4, epochs=3, lr=0.05, batch=64, seed=1)
    g.fit(CollectionSentenceIterator(_synthetic_corpus(60)))
    vocab_before = list(g.cache.index_to_word)
    w_before = np.asarray(g.w).copy()
    g.fit_cooccurrences(
        [("day", "sun", 5.0), ("night", "moon", 4.0),
         ("unseenword", "day", 3.0)]  # OOV member -> triple dropped
    )
    assert list(g.cache.index_to_word) == vocab_before  # vocab untouched
    assert "unseenword" not in g.cache.vocab
    # weights continued from the trained state, not re-initialized: the
    # rows not touched by the two surviving triples are bit-identical
    untouched = [
        g.cache.index_of(w) for w in vocab_before
        if w not in ("day", "sun", "night", "moon")
    ]
    assert np.allclose(np.asarray(g.w)[untouched], w_before[untouched])
    # a fresh model still builds its vocab from the triples
    g2 = Glove(layer_size=8, epochs=2, batch=8, seed=2)
    g2.fit_cooccurrences([("a", "b", 2.0), ("b", "c", 1.5)])
    assert len(g2.cache) == 3


@pytest.mark.slow
def test_paragraph_vectors_dbow():
    rng = np.random.default_rng(5)
    pairs = []
    for _ in range(100):
        pairs.append(("daytime", " ".join(rng.choice(["day", "sun", "light", "bright"], 5))))
        pairs.append(("nighttime", " ".join(rng.choice(["night", "moon", "dark", "stars"], 5))))
    pv = ParagraphVectors(layer_size=16, epochs=12, lr=0.05, seed=6, train_words=True)
    pv.fit_labeled(pairs)
    assert pv.get_label_vector("daytime") is not None
    assert pv.infer_nearest_label("sun light bright day") == "daytime"
    assert pv.infer_nearest_label("moon stars dark night") == "nighttime"


@pytest.mark.slow
def test_paragraph_vectors_negative_sampling():
    """PV-DBOW through the negative-sampling kernel (≙ iterateSample's
    negative branch, InMemoryLookupTable.java:217-243, reached via the
    inherited ParagraphVectors path): same-topic label vectors cluster,
    cross-topic ones don't."""
    rng = np.random.default_rng(0)
    topics = [
        ["day", "sun", "light", "bright"],
        ["night", "moon", "dark", "stars"],
        ["cat", "dog", "pet", "fur"],
        ["car", "road", "drive", "wheel"],
    ]
    fillers = [f"w{k}" for k in range(200)]
    docs = []
    for i in range(1000):
        words = list(rng.choice(topics[i % 4], 5)) + list(
            rng.choice(fillers, 5)
        )
        rng.shuffle(words)
        docs.append((f"doc{i}", " ".join(words)))
    pv = ParagraphVectors(
        layer_size=32, epochs=8, lr=0.05, seed=6, train_words=False,
        use_hierarchical_softmax=False, negative=5,
    )
    pv.fit_labeled(docs)
    vecs = np.stack([pv.get_label_vector(f"doc{i}") for i in range(120)])
    vecs /= np.linalg.norm(vecs, axis=1, keepdims=True) + 1e-9
    sims = vecs @ vecs.T
    same = np.mean(
        [sims[i, j] for i in range(120) for j in range(120)
         if i != j and i % 4 == j % 4]
    )
    cross = np.mean(
        [sims[i, j] for i in range(120) for j in range(120) if i % 4 != j % 4]
    )
    # statistical gate — stamp-time margin (2026-07-31, jax 0.9.0 CPU):
    # measured same=0.930, cross=0.305 (margin 0.625 vs the 0.3 bound).
    # A jaxlib/hardware change can move this with no repo bug: triage a
    # lone failure here as environment drift before code regression.
    assert same > cross + 0.3, (same, cross)


def test_paragraph_vectors_freezes_words_and_scratch_padding():
    """train_words=False must leave word vectors untouched even when the
    pair stream is not a whole number of batches (the padded tail rides
    on the scratch row, not word row 0)."""
    docs = [("a", "day sun light"), ("b", "night moon dark")]
    pv = ParagraphVectors(
        layer_size=8, epochs=3, lr=0.1, seed=2, train_words=False,
    )
    from deeplearning4j_tpu.nlp.sentence_iterator import (
        CollectionSentenceIterator,
    )

    pv.build_vocab(CollectionSentenceIterator([s for _, s in docs]))
    pv.reset_weights()
    syn0_before = np.asarray(pv.syn0).copy()
    pv.fit_labeled(docs)
    np.testing.assert_array_equal(np.asarray(pv.syn0), syn0_before)
    assert pv.syn0_labels.shape == (2, 8)


def test_rntn_refit_grows_per_label_tables():
    """A later fit with unseen productions must grow the tables, not
    silently clamp the new indices onto the last slot."""
    from deeplearning4j_tpu.models.rntn import RNTN
    from deeplearning4j_tpu.nlp.tree import parse_ptb

    m = RNTN(
        num_classes=2, dim=4, seed=0, max_nodes=16,
        simplified_model=False, combine_classification=False,
    )
    m.fit_trees([parse_ptb("(S (A a) (B b))")], epochs=1)
    n1 = m.params["W"].shape[0]
    m.fit_trees([parse_ptb("(S (C c) (D d))")], epochs=1)
    assert len(m.prod_index) > n1
    assert m.params["W"].shape[0] == len(m.prod_index)
    assert m.params["Wc_un"].shape[0] == len(m.unary_index)
    assert m._adagrad["W"].shape == m.params["W"].shape


def test_vocab_fit_texts_native_matches_fit():
    """fit_texts (native tokenizer+counter) == fit over the same tokens."""
    from deeplearning4j_tpu.nlp.vocab import VocabCache

    texts = ["the cat sat on the mat", "the dog sat", "cat and dog play"]
    toks = [t.split() for t in texts]
    a = VocabCache(min_word_frequency=1).fit(toks)
    b = VocabCache(min_word_frequency=1).fit_texts(texts)
    assert set(a.words()) == set(b.words())
    for w in a.words():
        assert a.word_frequency(w) == b.word_frequency(w)
    assert a.total_word_count == b.total_word_count


def test_sg_pairs_chunk_native_fallback_parity():
    """Native C++ pair enumeration == the numpy fallback, bit for bit
    (same splitmix64 stream, same emission order)."""
    from deeplearning4j_tpu import native_io as nio

    if not nio.available():
        pytest.skip("no g++ toolchain; parity test needs the native lib")
    rng = np.random.default_rng(5)
    sents = [
        rng.integers(0, 100, size=n).astype(np.int32)
        for n in [1, 2, 7, 30, 0, 3]
    ]
    a = nio.sg_pairs_chunk(sents, 4, 99)
    saved = (nio._lib, nio._tried)
    try:
        nio._lib, nio._tried = None, True
        b = nio.sg_pairs_chunk(sents, 4, 99)
    finally:
        nio._lib, nio._tried = saved
    assert len(a[0]) == len(b[0]) > 0
    assert (a[0] == b[0]).all() and (a[1] == b[1]).all()
    # every pair respects the window and comes from one sentence
    concat = np.concatenate([s for s in sents])
    assert set(a[0].tolist()) <= set(concat.tolist())


def test_hs_scan_matches_sequential_steps():
    """One scanned dispatch of k HS batches == k sequential _hs_step calls."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.word2vec import _hs_math, _hs_scan

    V, D, L, B, K = 30, 8, 5, 16, 3
    key = jax.random.key(0)
    ks = jax.random.split(key, 6)
    syn0 = jax.random.normal(ks[0], (V, D)) * 0.1
    syn1 = jax.random.normal(ks[1], (V - 1, D)) * 0.1
    codes = (jax.random.uniform(ks[2], (V, L)) > 0.5).astype(jnp.float32)
    points = jax.random.randint(ks[3], (V, L), 0, V - 1)
    mask = (jax.random.uniform(ks[4], (V, L)) > 0.2).astype(jnp.float32)
    ins = jax.random.randint(ks[5], (K, B), 0, V)
    tgts = jax.random.randint(ks[0], (K, B), 0, V)
    lrs = jnp.full((K,), 0.05, jnp.float32)

    s0, s1 = syn0, syn1
    for k in range(K):
        s0, s1 = _hs_math(s0, s1, ins[k], codes[tgts[k]], points[tgts[k]], mask[tgts[k]], lrs[k])
    a0, a1 = _hs_scan(jnp.array(syn0), jnp.array(syn1), ins, tgts, codes, points, mask, lrs)
    assert jnp.max(jnp.abs(a0 - s0)) < 1e-5
    assert jnp.max(jnp.abs(a1 - s1)) < 1e-5


def test_word2vec_many_epochs_stays_bounded():
    """Saturated-dot updates must be skipped (reference exp-table range
    check) — clipping instead diverges on small corpora at high epochs."""
    import numpy as np

    from deeplearning4j_tpu.models.word2vec import Word2Vec
    from deeplearning4j_tpu.nlp.sentence_iterator import (
        CollectionSentenceIterator,
    )

    corpus = [
        "the day was bright and the night was dark",
        "day follows night and night follows day",
    ] * 100
    w2v = Word2Vec(layer_size=16, window=3, min_word_frequency=1, seed=7,
                   epochs=15)
    s = CollectionSentenceIterator(corpus)
    w2v.build_vocab(s)
    s.reset()
    w2v.fit(s)
    syn0 = np.asarray(w2v.syn0)
    assert np.isfinite(syn0).all()
    assert np.abs(syn0).max() < 50.0, np.abs(syn0).max()
    assert np.isfinite(w2v.similarity("day", "night"))


def test_context_label_retriever():
    """≙ ContextLabelRetriever.stringWithLabels span extraction."""
    from deeplearning4j_tpu.nlp.vectorizers import string_with_labels

    clean, spans = string_with_labels(
        "the <ORG> acme corp </ORG> hired <PER> jane </PER> today"
    )
    assert clean == "the acme corp hired jane today"
    assert spans == {(1, 3): "ORG", (4, 5): "PER"}

    with pytest.raises(ValueError, match="no begin label"):
        string_with_labels("oops </ORG> here")
    with pytest.raises(ValueError, match="unclosed"):
        string_with_labels("<ORG> acme corp")
    with pytest.raises(ValueError, match="mismatch"):
        string_with_labels("<ORG> acme </PER>")
