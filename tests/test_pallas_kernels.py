"""Pallas kernels validated in interpret mode against the XLA references
(the lowered TPU path runs the identical kernel code on real chips)."""

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ops.attention import attention
from deeplearning4j_tpu.ops.pallas_kernels import flash_attention, fused_embedding_dot


def test_flash_attention_matches_dense():
    ks = jax.random.split(jax.random.key(0), 3)
    q, k, v = (jax.random.normal(kk, (2, 64, 2, 16)) for kk in ks)
    out = flash_attention(q, k, v, block_q=16, block_k=16, interpret=True)
    ref = attention(q, k, v)
    assert jnp.max(jnp.abs(out - ref)) < 1e-4


def test_fused_embedding_dot_matches_xla():
    ks = jax.random.split(jax.random.key(1), 3)
    b, L, d = 64, 7, 32
    h = jax.random.normal(ks[0], (b, d))
    w = jax.random.normal(ks[1], (b, L, d))
    mask = (jax.random.uniform(ks[2], (b, L)) > 0.3).astype(jnp.float32)
    out = fused_embedding_dot(h, w, mask, block_b=32, interpret=True)
    ref = jax.nn.sigmoid(jnp.clip(jnp.einsum("bd,bld->bl", h, w), -6, 6)) * mask
    assert jnp.max(jnp.abs(out - ref)) < 1e-5


def test_flash_attention_trainable_grads_match_dense():
    """custom_vjp backward kernels (dQ, dK/dV) == autodiff through dense."""
    from deeplearning4j_tpu.ops.pallas_kernels import flash_attention_trainable

    ks = jax.random.split(jax.random.key(2), 3)
    q, k, v = (jax.random.normal(kk, (2, 32, 2, 8)) for kk in ks)

    def loss_flash(q, k, v):
        o = flash_attention_trainable(q, k, v, block_q=8, block_k=8, interpret=True)
        return jnp.sum(jnp.sin(o) * o)

    def loss_dense(q, k, v):
        return jnp.sum(jnp.sin(attention(q, k, v)) * attention(q, k, v))

    out_f = loss_flash(q, k, v)
    out_d = loss_dense(q, k, v)
    assert abs(float(out_f) - float(out_d)) < 1e-3
    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        assert float(jnp.max(jnp.abs(a - b))) < 1e-3


def test_flash_attention_causal_matches_dense():
    from deeplearning4j_tpu.ops.attention import attention
    from deeplearning4j_tpu.ops.pallas_kernels import flash_attention

    rng = np.random.default_rng(7)
    q, k, v = (
        jnp.asarray(rng.normal(size=(2, 256, 2, 16)).astype(np.float32))
        for _ in range(3)
    )
    out = flash_attention(q, k, v, block_q=64, block_k=64, causal=True)
    ref = attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_attention_trainable_causal_grads_match_dense():
    from deeplearning4j_tpu.ops.attention import attention
    from deeplearning4j_tpu.ops.pallas_kernels import flash_attention_trainable

    rng = np.random.default_rng(8)
    q, k, v = (
        jnp.asarray(rng.normal(size=(1, 128, 2, 8)).astype(np.float32))
        for _ in range(3)
    )

    def loss_flash(q, k, v):
        o = flash_attention_trainable(q, k, v, block_q=32, block_k=32, causal=True)
        return jnp.sum(o * jnp.cos(o))

    def loss_dense(q, k, v):
        o = attention(q, k, v, causal=True)
        return jnp.sum(o * jnp.cos(o))

    np.testing.assert_allclose(
        float(loss_flash(q, k, v)), float(loss_dense(q, k, v)), rtol=1e-5
    )
    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_flash_backward_f32_partials_escape_hatch():
    """The _DQ_PARTIALS_F32 debug flag (ADVICE r4) must produce correct
    grads through the f32-plane path so it is actually usable when
    triaging suspected device grad corruption. Inputs are bf16 — with
    f32 inputs the plane dtype is f32 either way and the flag would be
    a no-op (the flag's whole point is bf16-storage runs)."""
    from deeplearning4j_tpu.ops import pallas_kernels as pk
    from deeplearning4j_tpu.ops.attention import attention

    rng = np.random.default_rng(3)
    q, k, v = (
        jnp.asarray(rng.normal(size=(1, 128, 2, 8)).astype(np.float32))
        .astype(jnp.bfloat16)
        for _ in range(3)
    )

    def loss_dense(q, k, v):
        o = attention(q, k, v, causal=True)
        return jnp.sum(o * jnp.cos(o))

    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    old = pk._DQ_PARTIALS_F32
    pk._DQ_PARTIALS_F32 = True
    try:
        def loss_flash(q, k, v):
            o = pk.flash_attention_trainable(
                q, k, v, block_q=32, block_k=32, causal=True
            )
            return jnp.sum(o * jnp.cos(o))

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    finally:
        pk._DQ_PARTIALS_F32 = old
    # bf16 storage: tolerance scaled to bf16 resolution; grads of the
    # two paths must agree to within rounding, not diverge structurally
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=0.06, atol=3e-2,
        )


def _dense_decode_ref(q, kvcache, pos, n_kv_heads, layer):
    """Dense einsum oracle for one decode step against the packed cache."""
    b, g, hk = q.shape
    hd = hk // n_kv_heads
    kk = np.asarray(kvcache[layer, 0], np.float32)  # (B, T, hk)
    vv = np.asarray(kvcache[layer, 1], np.float32)
    t = kk.shape[1]
    qr = np.asarray(q, np.float32).reshape(b, g, n_kv_heads, hd)
    kr = kk.reshape(b, t, n_kv_heads, hd)
    vr = vv.reshape(b, t, n_kv_heads, hd)
    s = np.einsum("bghd,bthd->bght", qr, kr) / np.sqrt(hd)
    s[..., pos + 1:] = -np.inf
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bght,bthd->bghd", p, vr).reshape(b, g, hk)


def test_flash_decode_attention_matches_dense():
    """Direct interpret-mode gate on the decode kernel (GQA packing,
    pos masking at cache-padding rows, multi-block streaming) — the
    generate/decode parity tests exercise it only indirectly and mostly
    in the slow lane."""
    from deeplearning4j_tpu.ops.pallas_kernels import flash_decode_attention

    rng = np.random.default_rng(11)
    for b, g, n_kv, t, pos, layer in [
        (2, 1, 2, 32, 0, 0),       # pos at the first row (MHA)
        (2, 1, 2, 32, 31, 0),      # pos at the last valid row
        (1, 4, 2, 32, 13, 1),      # GQA groups, padded cache, layer 1
        (2, 2, 3, 24, 7, 0),       # non-pow2 head count, padding
    ]:
        hk = n_kv * 16
        n_layers = 2
        q = jnp.asarray(rng.normal(size=(b, g, hk)).astype(np.float32))
        cache = jnp.asarray(
            rng.normal(size=(n_layers, 2, b, t, hk)).astype(np.float32)
        )
        out = flash_decode_attention(
            q, cache, jnp.int32(pos), n_kv, layer=layer, block_t=8,
            interpret=True,
        )
        ref = _dense_decode_ref(q, cache, pos, n_kv, layer)
        np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5)


def test_flash_decode_attention_int8_cache_matches_dequant_oracle():
    """int8-cache mode (r5 serving path): the kernel runs BOTH cache
    dots natively int8 on the MXU — the query row is quantized
    in-register (one scale per group) and the softmax weights are
    quantized per tile for the V contraction. The oracle applies the
    same q/k/v quantization explicitly; the residual difference is the
    in-kernel p-quantization (bounded by pmax/254 per weight, ~0.5% of
    the output scale here — measured 5.3e-3 at stamp time)."""
    from deeplearning4j_tpu.ops.pallas_kernels import flash_decode_attention

    rng = np.random.default_rng(3)
    for b, g, n_kv, t, pos, layer in [
        (2, 1, 2, 32, 31, 0),
        (1, 4, 2, 32, 13, 1),
        (2, 2, 3, 24, 7, 0),
    ]:
        hk = n_kv * 16
        n_layers = 2
        q = jnp.asarray(rng.normal(size=(b, g, hk)).astype(np.float32))
        raw = rng.normal(size=(n_layers, 2, b, t, hk)).astype(np.float32)
        amax = np.maximum(np.abs(raw).max(-1, keepdims=True), 1e-8)
        scales = (amax / 127.0).astype(np.float32)
        qcache = np.clip(np.round(raw / scales), -127, 127).astype(np.int8)
        out = flash_decode_attention(
            jnp.asarray(q), jnp.asarray(qcache), jnp.int32(pos), n_kv,
            layer=layer, block_t=8, interpret=True,
            kv_scales=jnp.asarray(scales),
        )
        # oracle: quantize q exactly as the kernel does (per-group row)
        qmax = np.maximum(np.abs(q).max(-1, keepdims=True), 1e-8)
        qs = qmax / 127.0
        q_deq = np.clip(np.round(q / qs), -127, 127) * qs
        dequant = qcache.astype(np.float32) * scales
        ref = _dense_decode_ref(
            jnp.asarray(q_deq.astype(np.float32)), jnp.asarray(dequant),
            pos, n_kv, layer,
        )
        # residual = in-kernel softmax-weight quantization, which the
        # oracle does not model (bounded by pmax/254 per weight)
        np.testing.assert_allclose(np.asarray(out), ref, atol=2.5e-2)


def test_flash_attention_noncausal_unchanged():
    from deeplearning4j_tpu.ops.attention import attention
    from deeplearning4j_tpu.ops.pallas_kernels import flash_attention

    rng = np.random.default_rng(9)
    q, k, v = (
        jnp.asarray(rng.normal(size=(2, 128, 2, 16)).astype(np.float32))
        for _ in range(3)
    )
    out = flash_attention(q, k, v, block_q=64, block_k=64)
    ref = attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def _scatter_slab_to_blocks(slab, tables, block_size, n_blocks):
    """Pack a contiguous (nl, 2, B, T, HK) slab into a block pool per
    the given (B, T//bs) int32 table — the layout the paged serving
    pool maintains incrementally (block id 0 = zero sentinel)."""
    nl, two, b, t, hk = slab.shape
    blocks = np.zeros((nl, two, n_blocks, block_size, hk), slab.dtype)
    for i in range(b):
        for j in range(t // block_size):
            blocks[:, :, tables[i, j]] = (
                slab[:, :, i, j * block_size:(j + 1) * block_size]
            )
    return blocks


def test_flash_decode_paged_bitwise_matches_slab_kernel():
    """The paged kernel (scalar-prefetch block tables, block-by-block
    HBM gather) is BITWISE the slab kernel at block_t=block_size over
    the gathered cache — same tile partitioning, same accumulation
    order. Tables are shuffled and one block is aliased across rows,
    so the lookup path really is exercised."""
    from deeplearning4j_tpu.ops.pallas_kernels import (
        flash_decode_attention,
        flash_decode_attention_paged,
    )

    rng = np.random.default_rng(17)
    b, g, n_kv, t, bs, layer = 2, 2, 2, 32, 8, 1
    hk = n_kv * 16
    bps = t // bs
    q = jnp.asarray(rng.normal(size=(b, g, hk)).astype(np.float32))
    slab = rng.normal(size=(2, 2, b, t, hk)).astype(np.float32)
    # shuffled 1-based ids; alias row 1's first block to row 0's (the
    # prefix-sharing case) AFTER building the slab view accordingly
    tables = (rng.permutation(b * bps) + 1).reshape(b, bps).astype(np.int32)
    tables[1, 0] = tables[0, 0]
    slab[:, :, 1, :bs] = slab[:, :, 0, :bs]
    blocks = _scatter_slab_to_blocks(slab, tables, bs, b * bps + 1)
    pos = jnp.asarray(np.array([31, 13], np.int32))
    out_paged = flash_decode_attention_paged(
        q, jnp.asarray(blocks), jnp.asarray(tables), pos, n_kv,
        layer=layer, interpret=True,
    )
    out_slab = flash_decode_attention(
        q, jnp.asarray(slab), pos, n_kv, layer=layer, block_t=bs,
        interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(out_paged),
                                  np.asarray(out_slab))


def test_flash_decode_paged_int8_bitwise_matches_slab_int8():
    """int8 paged mode: per-row dequant scales ride in their own block
    pool (same tables) and the fused dequant is bitwise the slab int8
    kernel's — the HBM stream stays int8 bytes + table ints."""
    from deeplearning4j_tpu.ops.pallas_kernels import (
        flash_decode_attention,
        flash_decode_attention_paged,
    )

    rng = np.random.default_rng(23)
    b, g, n_kv, t, bs, layer = 2, 1, 2, 24, 8, 0
    hk = n_kv * 16
    bps = t // bs
    q = jnp.asarray(rng.normal(size=(b, g, hk)).astype(np.float32))
    raw = rng.normal(size=(2, 2, b, t, hk)).astype(np.float32)
    amax = np.maximum(np.abs(raw).max(-1, keepdims=True), 1e-8)
    scales = (amax / 127.0).astype(np.float32)
    qslab = np.clip(np.round(raw / scales), -127, 127).astype(np.int8)
    tables = (rng.permutation(b * bps) + 1).reshape(b, bps).astype(np.int32)
    n_blocks = b * bps + 1
    qblocks = _scatter_slab_to_blocks(qslab, tables, bs, n_blocks)
    sblocks = _scatter_slab_to_blocks(scales, tables, bs, n_blocks)
    pos = jnp.asarray(np.array([23, 7], np.int32))
    out_paged = flash_decode_attention_paged(
        q, jnp.asarray(qblocks), jnp.asarray(tables), pos, n_kv,
        layer=layer, interpret=True, block_scales=jnp.asarray(sblocks),
    )
    out_slab = flash_decode_attention(
        q, jnp.asarray(qslab), pos, n_kv, layer=layer, block_t=bs,
        interpret=True, kv_scales=jnp.asarray(scales),
    )
    np.testing.assert_array_equal(np.asarray(out_paged),
                                  np.asarray(out_slab))


def test_flash_decode_paged_sentinel_blocks_are_invisible():
    """Unallocated table entries point at the zero sentinel (id 0);
    rows past ``pos`` are masked anyway, so a short sequence in a
    sparsely-allocated table matches the dense reference."""
    from deeplearning4j_tpu.ops.pallas_kernels import (
        flash_decode_attention_paged,
    )

    rng = np.random.default_rng(29)
    b, g, n_kv, t, bs = 1, 1, 2, 32, 8
    hk = n_kv * 16
    bps = t // bs
    q = jnp.asarray(rng.normal(size=(b, g, hk)).astype(np.float32))
    slab = rng.normal(size=(2, 2, b, t, hk)).astype(np.float32)
    pos = 5  # only the first block is live
    tables = np.zeros((b, bps), np.int32)
    tables[0, 0] = 3  # arbitrary pool slot; the rest stay sentinel
    blocks = np.zeros((2, 2, 8, bs, hk), np.float32)
    blocks[:, :, 3] = slab[:, :, 0, :bs]
    out = flash_decode_attention_paged(
        q, jnp.asarray(blocks), jnp.asarray(tables),
        jnp.asarray(np.array([pos], np.int32)), n_kv, layer=0,
        interpret=True,
    )
    ref = _dense_decode_ref(q, jnp.asarray(slab), pos, n_kv, 0)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5)
