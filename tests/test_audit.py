"""graftaudit suite: the program-surface registry + jaxpr auditor.

Mirrors ``test_analysis.py``'s structure: each audit check is fed a
seeded violation of the exact bug class it exists for (an injected
bf16->f32 upcast, a donation with no consuming output, a tampered
collective contract, a smuggled host callback, a blown flop/memory
budget, a hole in the compile surface) and must flag it while staying
quiet on the blessed shape next to it. Plus the two load-bearing
meta-tests: the shipped registry audits clean against the committed
``.graftaudit.json``, and a live engine's observed jit-cache keys all
fall inside the surface the registry enumerates for the same geometry.
The interprocedural host-sync lint (call-graph propagation) is covered
here too, next to the auditor it upgraded alongside.
"""

import dataclasses
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.analysis.audit import (
    AuditFinding,
    check_budgets,
    check_callbacks,
    check_collectives,
    check_donation,
    check_dtype,
    check_surface,
    budget_representatives,
    default_baseline_path,
    load_baseline,
    main as audit_main,
    measure_spec,
    run_audit,
)
from deeplearning4j_tpu.analysis.core import ModuleInfo
from deeplearning4j_tpu.analysis.programs import (
    ProgramSpec,
    ServingGeometry,
    default_audit_config,
    default_audit_geometry,
    enumerate_programs,
    expected_surface,
    live_engine_families,
)
from deeplearning4j_tpu.analysis.rules import run_rules
from deeplearning4j_tpu.models.transformer import TransformerConfig


def _spec(name, fn, args, donate=(), tp=False, collectives=None):
    """A minimal hand-rolled ProgramSpec for single-check tests."""
    return ProgramSpec(
        name=name, family="synthetic", donate=tuple(donate), tp=tp,
        collectives=dict(collectives or {}), build=lambda: (fn, args),
    )


def _f32(*shape):
    return jax.ShapeDtypeStruct(shape, jnp.float32)


def _bf16(*shape):
    return jax.ShapeDtypeStruct(shape, jnp.bfloat16)


# -- check: dtype promotion -----------------------------------------------


def test_dtype_counts_injected_f32_upcast():
    def leaky(p):
        # the seeded bug: a bf16 intermediate silently promoted to f32
        return (p.astype(jnp.float32) * 2.0).astype(jnp.bfloat16)

    spec = _spec("leaky", leaky, (_bf16(8),))
    rec = measure_spec(spec)
    assert rec["f32_upcasts"] == 1
    # drift vs the reviewed baseline is the finding...
    fs = check_dtype(spec, rec, {"f32_upcasts": 0})
    assert [f.check for f in fs] == ["dtype"]
    assert "drifted" in fs[0].message
    # ...a matching baseline (the reviewed upcast) is clean
    assert check_dtype(spec, rec, {"f32_upcasts": 1}) == []


def test_dtype_flags_f64_unconditionally():
    spec = _spec("wide", lambda p: p, (_f32(4),))
    rec = dict(measure_spec(spec), f64_casts=1)
    fs = check_dtype(spec, rec, None)  # no baseline needed
    assert [f.check for f in fs] == ["dtype"]
    assert "float64" in fs[0].message


def test_dtype_pure_bf16_program_is_clean():
    spec = _spec("pure", lambda p: p * jnp.bfloat16(2), (_bf16(8),))
    rec = measure_spec(spec)
    assert rec["f32_upcasts"] == 0
    assert check_dtype(spec, rec, {"f32_upcasts": 0}) == []


# -- check: donation ------------------------------------------------------


def test_donation_gap_when_output_cannot_consume_arg():
    # the seeded bug: a cache arg declared donated, but the program
    # stopped returning the updated cache — aliasing silently dies
    spec = _spec("drop", lambda c: c.sum(), (_f32(4, 4),), donate=(0,))
    rec = measure_spec(spec)
    assert rec["donation_unused"]
    fs = check_donation(spec, rec)
    assert [f.check for f in fs] == ["donation"]
    assert "donation not used" in fs[0].message


def test_donation_matching_output_is_clean():
    spec = _spec("ok", lambda c: c + 1, (_f32(4, 4),), donate=(0,))
    rec = measure_spec(spec)
    assert rec["donation_unused"] == []
    assert check_donation(spec, rec) == []


def test_donation_matches_pytree_leaves_by_shape_and_dtype():
    caches = {"k": _f32(2, 8), "v": _f32(2, 8)}

    def update(c, x):
        return {"k": c["k"] + x, "v": c["v"] * x}, x.sum()

    spec = _spec("tree", update, (caches, _f32(2, 8)), donate=(0,))
    rec = measure_spec(spec)
    assert rec["donation_unused"] == []


# -- check: collective signature ------------------------------------------


@pytest.fixture(scope="module")
def tp_replay_record():
    if jax.device_count() < 2:
        pytest.skip("needs >= 2 devices (conftest forces 8 on CPU)")
    geom = dataclasses.replace(
        default_audit_geometry(), tp=2, n_adapters=0
    )
    specs = enumerate_programs(default_audit_config(), geom)
    (spec,) = [s for s in specs if s.name == "replay[tp=2]"]
    return spec, measure_spec(spec)


def test_collectives_match_declared_contract(tp_replay_record):
    spec, rec = tp_replay_record
    assert rec["collectives"]  # the TP program really has collectives
    assert check_collectives(spec, rec) == []


def test_collectives_flag_contract_drift(tp_replay_record):
    spec, rec = tp_replay_record
    tampered = dataclasses.replace(
        spec, collectives={"sharding_constraint": 1}
    )
    fs = check_collectives(tampered, rec)
    assert [f.check for f in fs] == ["collectives"]
    assert "TP parity" in fs[0].message


def test_collectives_flag_stray_collective_in_single_chip(
        tp_replay_record):
    # the seeded bug: a collective leaking into a single-chip family
    spec, rec = tp_replay_record
    stray = dataclasses.replace(spec, tp=False, collectives={})
    fs = check_collectives(stray, rec)
    assert [f.check for f in fs] == ["collectives"]
    assert "single-chip" in fs[0].message


def test_collectives_single_chip_clean_program():
    spec = _spec("plain", lambda p: p + 1, (_f32(4),))
    rec = measure_spec(spec)
    assert rec["collectives"] == {}
    assert check_collectives(spec, rec) == []


# -- check: host callbacks ------------------------------------------------


def test_callbacks_flag_smuggled_debug_print():
    def chatty(p):
        jax.debug.print("p0={}", p[0])  # the seeded bug
        return p + 1

    spec = _spec("chatty", chatty, (_f32(4),))
    rec = measure_spec(spec)
    assert "debug_callback" in rec["callbacks"]
    fs = check_callbacks(spec, rec)
    assert [f.check for f in fs] == ["callbacks"]


def test_callbacks_flag_smuggled_pure_callback():
    def smuggler(p):
        host = jax.pure_callback(
            lambda a: np.sin(a), jax.ShapeDtypeStruct((4,), np.float32),
            p,
        )
        return p + host

    spec = _spec("smuggler", smuggler, (_f32(4),))
    rec = measure_spec(spec)
    assert "pure_callback" in rec["callbacks"]
    assert check_callbacks(spec, rec)


def test_callbacks_clean_program():
    spec = _spec("quiet", lambda p: p + 1, (_f32(4),))
    assert check_callbacks(spec, measure_spec(spec)) == []


# -- check: memory/flop budgets -------------------------------------------


def test_budget_measurement_populates_flops_and_temp():
    spec = _spec("mm", lambda a, b: a @ b, (_f32(16, 16), _f32(16, 16)))
    rec = measure_spec(spec, budgets=True)
    assert rec["flops"] and rec["flops"] > 0
    assert rec["temp_bytes"] is not None
    assert rec["arg_bytes"] == 2 * 16 * 16 * 4
    assert rec["out_bytes"] == 16 * 16 * 4


def test_budget_flags_blown_flop_and_temp_budget():
    spec = _spec("hog", lambda p: p, (_f32(4),))
    rec = {"arg_bytes": 100, "out_bytes": 50, "flops": 1000.0,
           "temp_bytes": 4096}
    base = {"arg_bytes": 100, "out_bytes": 50, "flops": 500.0,
            "temp_bytes": 2048}
    fs = check_budgets(spec, rec, base)
    assert sorted(f.check for f in fs) == ["budget", "budget"]
    assert any("flops regression" in f.message for f in fs)
    assert any("temp_bytes regression" in f.message for f in fs)


def test_budget_within_tolerance_is_clean():
    spec = _spec("ok", lambda p: p, (_f32(4),))
    rec = {"arg_bytes": 100, "out_bytes": 50, "flops": 1040.0,
           "temp_bytes": 2048}
    base = {"arg_bytes": 100, "out_bytes": 50, "flops": 1000.0,
            "temp_bytes": 2048}
    assert check_budgets(spec, rec, base) == []


def test_budget_flags_aval_surface_move():
    spec = _spec("grew", lambda p: p, (_f32(4),))
    rec = {"arg_bytes": 128, "out_bytes": 50, "flops": None,
           "temp_bytes": None}
    base = {"arg_bytes": 100, "out_bytes": 50}
    fs = check_budgets(spec, rec, base)
    assert [f.check for f in fs] == ["budget"]
    assert "arg_bytes changed" in fs[0].message


def test_budget_representatives_pick_family_envelopes():
    geom = dataclasses.replace(
        default_audit_geometry(), tp=1, n_adapters=0
    )
    specs = enumerate_programs(default_audit_config(), geom)
    reps = budget_representatives(specs)
    # one per family; the keyed families contribute their LARGEST member
    assert "step[K=2]" in reps and "step[K=1]" not in reps
    assert "prefill[b=32]" in reps and "prefill[b=8]" not in reps
    assert "batch_prefill[b=32,n=4]" in reps
    assert "replay" in reps  # singletons are their own envelope


# -- check: compile surface -----------------------------------------------


def test_surface_clean_on_full_enumeration():
    cfg = default_audit_config()
    geom = ServingGeometry()
    specs = enumerate_programs(cfg, geom)
    assert check_surface(cfg, geom, specs) == []


def test_surface_flags_missing_bucket_and_singleton():
    cfg = default_audit_config()
    geom = ServingGeometry()
    specs = enumerate_programs(cfg, geom)
    holey = [s for s in specs
             if s.name not in ("prefill[b=16]", "seg_store")]
    fs = check_surface(cfg, geom, holey)
    assert any(f.program == "prefill" and "buckets" in f.message
               for f in fs)
    assert any("seg_store" in f.message for f in fs)


def test_surface_flags_duplicate_and_off_grid_programs():
    cfg = default_audit_config()
    geom = ServingGeometry()
    specs = enumerate_programs(cfg, geom)
    fs = check_surface(cfg, geom, specs + [specs[0]])
    assert any("duplicate" in f.message for f in fs)
    # a request-shaped key off the pow2 grid (the retrace bug class
    # CompileCountGuard catches at runtime, caught statically here)
    rogue = dataclasses.replace(specs[0], name="prefill[b=13]")
    fs = check_surface(cfg, geom, specs + [rogue])
    assert any(f.program == "prefill" for f in fs)


# -- the committed baseline + repo meta-test ------------------------------


def test_repo_audits_clean_against_committed_baseline():
    """Load-bearing: the shipped registry, audited against the
    committed ``.graftaudit.json``, has zero findings (CI runs the
    same check via ``python -m deeplearning4j_tpu audit --strict``).
    Trace-only here: the budget compiles have their own test and CI
    leg."""
    cfg = default_audit_config()
    geom = default_audit_geometry()
    tp_skipped = False
    if jax.device_count() < geom.tp:  # pragma: no cover - env guard
        geom = dataclasses.replace(geom, tp=1)
        tp_skipped = True
    baseline = load_baseline(default_baseline_path())
    assert baseline is not None, "commit .graftaudit.json"
    records, findings, stale, errors = run_audit(
        cfg, geom, baseline=baseline, budgets="none"
    )
    if tp_skipped:  # pragma: no cover - env guard
        stale = [n for n in stale if "[tp=" not in n]
    assert errors == []
    assert [f.render() for f in findings] == []
    assert stale == []
    assert len(records) == len(baseline["programs"]) or tp_skipped


def test_registry_surface_matches_committed_geometry():
    """The committed baseline's cfg/geometry blocks reproduce the
    committed program list exactly — renaming a family or moving the
    grid without --write-baseline must show up as a diff here."""
    baseline = load_baseline(default_baseline_path())
    cfg = TransformerConfig.from_json(json.dumps(baseline["cfg"]))
    geom = ServingGeometry(**baseline["geometry"])
    if jax.device_count() < geom.tp:  # pragma: no cover - env guard
        pytest.skip("needs the TP surface (conftest forces 8 devices)")
    names = {s.name for s in enumerate_programs(cfg, geom)}
    assert names == set(baseline["programs"])


# -- registry vs live engine ----------------------------------------------


@pytest.fixture(scope="module")
def tiny_serving():
    from deeplearning4j_tpu.models.transformer import init_transformer

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=1, d_ff=64,
        max_len=32,
    )
    return cfg, init_transformer(jax.random.key(0), cfg)


def test_live_engine_families_inside_registry_surface(tiny_serving):
    """The acceptance diff: every jit-cache key a LIVE engine compiles
    is enumerated by the registry for the same geometry — the auditor
    really audits the programs the engine runs, not a lookalike."""
    from deeplearning4j_tpu.analysis.sanitizers import CompileCountGuard
    from deeplearning4j_tpu.serving import Request, ServingEngine

    cfg, params = tiny_serving
    eng = ServingEngine(
        cfg, params, n_slots=2, temperature=0.0, decode_horizon=2,
        adaptive_horizon=True, prefill_max_bucket=16,
    )
    rng = np.random.default_rng(0)
    for i in range(4):
        eng.scheduler.submit(Request(
            id=f"r{i}",
            prompt=rng.integers(1, 60, (3 + 3 * i,)).astype(np.int32),
            max_new=4,
        ))
    results = eng.run()
    assert len(results) == 4
    CompileCountGuard(eng).assert_ok()

    geom = ServingGeometry(
        n_slots=2, max_total=cfg.max_len, decode_horizon=2,
        adaptive_horizon=True, prefill_max_bucket=16,
    )
    exp = expected_surface(cfg, geom)
    got = live_engine_families(eng)
    assert got["step"] <= exp["step"]
    assert got["prefill"] <= exp["prefill"]
    assert got["chunk"] <= exp["chunk"]
    assert got["batch_prefill"] <= exp["batch_prefill"]
    assert got["batch_hit"] <= exp["batch_hit"]
    assert got["singletons"] <= exp["singletons"]
    # and the registry enumerates a spec for every observed key
    names = {s.name for s in enumerate_programs(cfg, geom)}
    for k in got["step"]:
        assert f"step[K={k}]" in names
    for b in got["prefill"]:
        assert f"prefill[b={b}]" in names
    for b, n in got["batch_prefill"]:
        assert f"batch_prefill[b={b},n={n}]" in names
    assert got["singletons"] <= {
        s.name for s in enumerate_programs(cfg, geom)
    }


# -- audit CLI exit codes -------------------------------------------------


def _tiny_audit_surface(monkeypatch):
    """Shrink the CLI's default surface to a 13-program grid that
    traces in well under a second, and skip the budget compiles (the
    budget machinery has its own tests above)."""
    from deeplearning4j_tpu.analysis import audit as audit_mod
    from deeplearning4j_tpu.analysis import programs as programs_mod

    monkeypatch.setattr(
        programs_mod, "default_audit_config",
        lambda: TransformerConfig(
            vocab_size=64, d_model=32, n_heads=2, n_kv_heads=2,
            n_layers=1, d_ff=64, max_len=16,
            compute_dtype=jnp.bfloat16, decode_kernel=False,
        ),
    )
    monkeypatch.setattr(
        programs_mod, "default_audit_geometry",
        lambda: ServingGeometry(
            n_slots=2, max_total=16, decode_horizon=1,
            adaptive_horizon=False, prefill_max_bucket=8, tp=1,
            n_adapters=0, prefix_segments=1,
        ),
    )
    monkeypatch.setattr(
        audit_mod, "budget_representatives", lambda specs: set()
    )


def test_audit_cli_exit_codes(tmp_path, monkeypatch):
    _tiny_audit_surface(monkeypatch)
    bl = tmp_path / ".graftaudit.json"
    report = tmp_path / "report.json"
    assert audit_main(["--baseline", str(bl), "--write-baseline"]) == 0
    assert audit_main(["--baseline", str(bl), "--strict",
                       "--json-out", str(report)]) == 0
    out = json.loads(report.read_text())
    assert out["findings"] == [] and out["programs"]

    data = json.loads(bl.read_text())
    assert data["version"] == 1
    # a program missing from the baseline is a finding outright
    dropped = dict(data, programs=dict(data["programs"]))
    del dropped["programs"]["logit_row"]
    bl.write_text(json.dumps(dropped))
    assert audit_main(["--baseline", str(bl)]) == 1
    # a stale entry only fails under --strict (mirrors graftlint)
    ghost = dict(data, programs=dict(data["programs"]))
    ghost["programs"]["ghost[b=99]"] = {"collectives": {}}
    bl.write_text(json.dumps(ghost))
    assert audit_main(["--baseline", str(bl)]) == 0
    assert audit_main(["--baseline", str(bl), "--strict"]) == 1
    assert audit_main(["--no-baseline"]) == 0


def test_audit_cli_rejects_unknown_baseline_version(tmp_path,
                                                    monkeypatch):
    _tiny_audit_surface(monkeypatch)
    bl = tmp_path / "bl.json"
    bl.write_text(json.dumps({"version": 99, "programs": {}}))
    with pytest.raises(ValueError, match="unsupported baseline"):
        audit_main(["--baseline", str(bl)])


# -- interprocedural host-sync lint ---------------------------------------


def _findings(src, rules=None):
    return run_rules(ModuleInfo("synthetic.py", src, "synthetic.py"),
                     rules=rules)


def test_host_sync_chain_through_helper():
    src = '''
import numpy as np

def helper(x):
    return np.asarray(x)

# lint: hot-path
def dispatch(x):
    return helper(x)
'''
    fs = _findings(src, ["host-sync"])
    assert [f.qualname for f in fs] == ["dispatch"]
    assert "'helper'" in fs[0].message and "syncs" in fs[0].message


def test_host_sync_transitive_chain_names_the_path():
    src = '''
import numpy as np

def deep(x):
    return np.asarray(x)

def middle(x):
    return deep(x)

# lint: hot-path
def hot(x):
    return middle(x)
'''
    fs = _findings(src, ["host-sync"])
    assert [f.qualname for f in fs] == ["hot"]
    assert "'middle'" in fs[0].message and "deep" in fs[0].message


def test_host_sync_sync_ok_at_source_kills_the_chain():
    src = '''
import numpy as np

def helper(x):
    return np.asarray(x)  # lint: sync-ok the designated readback

# lint: hot-path
def dispatch(x):
    return helper(x)
'''
    assert _findings(src, ["host-sync"]) == []


def test_host_sync_sync_ok_at_call_site_suppresses():
    src = '''
import numpy as np

def helper(x):
    return np.asarray(x)

# lint: hot-path
def dispatch(x):
    return helper(x)  # lint: sync-ok drained at horizon boundary
'''
    assert _findings(src, ["host-sync"]) == []


def test_host_sync_hot_callee_not_reflagged_through_caller():
    # the callee's own sync site is the one finding; its hot-path
    # callers are not re-flagged (annotating the source must not
    # require annotating every transitive caller)
    src = '''
import numpy as np

# lint: hot-path
def inner(x):
    return np.asarray(x)

# lint: hot-path
def outer(x):
    return inner(x)
'''
    fs = _findings(src, ["host-sync"])
    assert [f.qualname for f in fs] == ["inner"]


def test_host_sync_resolves_self_method_calls():
    src = '''
import numpy as np

class Engine:
    def _readback(self, x):
        return np.asarray(x)

    # lint: hot-path
    def dispatch(self, x):
        return self._readback(x)
'''
    fs = _findings(src, ["host-sync"])
    assert [f.qualname for f in fs] == ["Engine.dispatch"]
    assert "Engine._readback" in fs[0].message


def test_host_sync_cold_caller_of_syncing_helper_is_clean():
    src = '''
import numpy as np

def helper(x):
    return np.asarray(x)

def cold(x):
    return helper(x)
'''
    assert _findings(src, ["host-sync"]) == []


# -- run_audit seeded end-to-end ------------------------------------------


def test_run_audit_reports_new_program_against_baseline():
    """A family added without --write-baseline is itself a finding:
    the compile surface cannot grow silently."""
    cfg = default_audit_config()
    geom = ServingGeometry()
    specs = enumerate_programs(cfg, geom)
    baseline = {"version": 1, "programs": {}}
    records, findings, stale, errors = run_audit(
        cfg, geom, baseline=baseline, budgets="none"
    )
    assert errors == []
    assert len(records) == len(specs)
    missing = [f for f in findings if f.check == "baseline"]
    assert len(missing) == len(specs)
    assert all("not in baseline" in f.message for f in missing)


def test_finding_render_shape():
    f = AuditFinding("dtype", "step[K=2]", "boom")
    assert f.render() == "step[K=2]: [dtype] boom"
