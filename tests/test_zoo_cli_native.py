"""Model zoo (char-LSTM, AlexNet, recursive AE), CLI, cloud IO, native loader."""

import struct

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import native_io
from deeplearning4j_tpu.models.alexnet import build_alexnet, synthetic_cifar
from deeplearning4j_tpu.models.char_lstm import CharLSTM
from deeplearning4j_tpu.nn import conf as C
from deeplearning4j_tpu.nn import layers as L
from deeplearning4j_tpu.utils.cloud_io import LocalModelSaver, get_saver, render_tpu_vm_provision


def test_recursive_autoencoder_layer():
    mod = L.get("recursive_autoencoder")
    cfg = C.LayerConfig(layer_type="recursive_autoencoder", n_in=6, n_out=6, activation="tanh")
    p = mod.init(jax.random.key(0), cfg)
    x = jax.random.normal(jax.random.key(1), (4, 5, 6))
    h = mod.activate(p, cfg, x)
    assert h.shape == (4, 6)
    s0 = float(mod.score(p, cfg, x, jax.random.key(2)))
    step = jax.jit(
        lambda p, k: jax.tree.map(
            lambda a, g: a - 0.05 * g, p, mod.gradient(p, cfg, x, k)[1]
        )
    )
    for i in range(100):
        p = step(p, jax.random.key(i))
    s1 = float(mod.score(p, cfg, x, jax.random.key(3)))
    assert s1 < s0


def test_char_lstm_learns_and_samples():
    text = "hello world " * 40
    m = CharLSTM(seq_len=12, lr=1.0, seed=0)
    losses = m.fit(text, epochs=25, batch=8)
    assert losses[-1] < losses[0] * 0.3, losses
    out = m.sample("h", length=20, rng_seed=1)
    assert len(out) == 21
    assert set(out) <= set(text)
    beams = m.beam_decode("h", beam_size=2, n_steps=4)
    assert beams and all(lp <= 0 for _, lp in beams)


@pytest.mark.slow
def test_alexnet_forward_and_one_step():
    net, params = build_alexnet(seed=0)
    ds = synthetic_cifar(16)
    out = net.output(ds.features[:4])
    assert out.shape == (4, 10)
    from deeplearning4j_tpu.models.lenet import lenet_loss

    loss_fn = lenet_loss(net)
    l0 = float(loss_fn(params, jnp.asarray(ds.features), jnp.asarray(ds.labels)))
    assert np.isfinite(l0)


def test_cli_train_and_provision(tmp_path, capsys):
    from deeplearning4j_tpu.cli import main

    rc = main(
        [
            "train", "--model", "lenet", "--epochs", "1", "--batch", "128",
            "--examples", "256", "--checkpoint-dir", str(tmp_path / "ck"),
            "--save-every", "1",
        ]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "final loss" in out
    assert list((tmp_path / "ck").glob("ckpt_*.npz"))

    rc = main(["provision", "mypod", "--zone", "us-east1-d"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "gcloud compute tpus tpu-vm create mypod" in out
    assert "--zone=us-east1-d" in out


@pytest.mark.slow
def test_cli_train_transformer_tp_orbax(tmp_path, capsys):
    from deeplearning4j_tpu.cli import main

    rc = main(
        [
            "train", "--model", "transformer", "--steps", "4",
            "--seq-len", "32", "--d-model", "32", "--batch", "8",
            "--tp", "2", "--checkpoint-dir", str(tmp_path / "ck"),
            "--checkpoint-backend", "orbax", "--save-every", "2",
        ]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "final loss" in out
    assert "sample:" in out
    # orbax checkpoints are durable (wait() ran); orbax always saves the
    # first step, then follows the save-every cadence
    steps = sorted(
        int(p.name) for p in (tmp_path / "ck").iterdir() if p.name.isdigit()
    )
    assert steps == [1, 2, 4]

    # the serving command restores the same checkpoint and samples —
    # plain, int8-weights quantized, and beam decode. NO model flags:
    # the trained config rides in the checkpoint meta
    common = [
        "generate", "--checkpoint-dir", str(tmp_path / "ck"),
        "--checkpoint-backend", "orbax",
        "--prompt", "the quick", "--max-new", "8",
    ]
    assert main(common) == 0
    out = capsys.readouterr().out
    assert "restored step 4" in out and "sample: the quick" in out
    assert main(common + ["--int8", "weights"]) == 0
    out = capsys.readouterr().out
    assert "int8 serving mode: weights" in out and "sample: the quick" in out
    assert main(common + ["--beam", "2"]) == 0
    out = capsys.readouterr().out
    assert "beam 0 (logp " in out and "beam 1 (logp " in out

    # a missing checkpoint fails cleanly, not with a traceback — and the
    # read-only command must not create the typo'd directory tree
    assert main(
        ["generate", "--checkpoint-dir", str(tmp_path / "empty")]
    ) == 1
    assert not (tmp_path / "empty").exists()


def test_cloud_io_local_and_dispatch(tmp_path):
    saver = get_saver(str(tmp_path))
    assert isinstance(saver, LocalModelSaver)
    path = saver.save(b"hello", "model.bin")
    assert saver.load("model.bin") == b"hello"
    assert path.endswith("model.bin")
    cmd = render_tpu_vm_provision("x")
    assert cmd[0] == "gcloud"


def test_native_loader_builds_and_matches_numpy(tmp_path):
    if not native_io.available():
        pytest.skip("no g++ toolchain; numpy fallback covered elsewhere")
    rng = np.random.default_rng(0)
    feats = rng.integers(0, 256, (50, 12), dtype=np.uint8)
    labels = rng.integers(0, 4, 50, dtype=np.uint8)

    # idx round-trip through the native reader
    p = tmp_path / "f-idx2-ubyte"
    with open(p, "wb") as fh:
        fh.write(struct.pack(">HBB", 0, 0x08, 2))
        fh.write(struct.pack(">II", 50, 12))
        fh.write(feats.tobytes())
    arr = native_io.read_idx(p)
    assert (arr == feats).all()

    asm = native_io.NativeBatchAssembler(feats, labels, num_classes=4, seed=7)
    x, y = asm.batch(0, 8)
    sel = asm.order[:8]
    assert np.allclose(x, feats[sel].astype(np.float32) / 255.0)
    assert (y.argmax(1) == labels[sel]).all()
    # deterministic shuffle for a fixed seed
    asm2 = native_io.NativeBatchAssembler(feats, labels, num_classes=4, seed=7)
    assert (asm.order == asm2.order).all()


def test_native_prefetching_loader_epochs_and_content():
    """Prefetcher yields correct one-hot batches and advances epochs with a
    reshuffle; works through the numpy fallback too."""
    rng = np.random.default_rng(1)
    feats = rng.integers(0, 256, (20, 6), dtype=np.uint8)
    labels = rng.integers(0, 3, 20, dtype=np.uint8)
    loader = native_io.PrefetchingLoader(
        feats, labels, num_classes=3, batch_size=8, seed=3, depth=2
    )
    try:
        label_of = {}
        for i in range(20):
            label_of[feats[i].tobytes()] = labels[i]
        seen_epochs = set()
        for _ in range(12):  # 12*8 rows > 4 epochs of 20
            x, y, ep = loader.next_batch()
            assert x.shape == (8, 6) and y.shape == (8, 3)
            assert x.min() >= 0.0 and x.max() <= 1.0
            seen_epochs.add(ep)
            for r in range(8):
                row_u8 = np.round(x[r] * 255.0).astype(np.uint8).tobytes()
                assert y[r].argmax() == label_of[row_u8]
                assert y[r].sum() == 1.0
        assert len(seen_epochs) >= 2, "epoch counter never advanced"
    finally:
        loader.close()


def test_native_vocab_counter_matches_python():
    texts = [
        "The quick brown fox jumps over the lazy dog",
        "the dog barks; the fox runs!  Don't stop",
        "fox fox FOX",
    ]
    words, counts, total = native_io.count_vocab(texts, min_count=1)
    assert total == 9 + 8 + 3
    d = dict(zip(words, counts.tolist()))
    assert d["the"] == 4
    assert d["fox"] == 5
    assert d["dog"] == 2
    assert d["don't"] == 1
    # sorted by count desc
    assert list(counts) == sorted(counts, reverse=True)
    # min_count filter
    w2, c2, _ = native_io.count_vocab(texts, min_count=2)
    assert set(w2) == {"the", "fox", "dog"}


def test_vocab_counter_non_ascii_parity():
    """Native (UTF-8 byte) tokenizer and the Python fallback agree on
    non-ASCII text: kept as token chars, only ASCII is case-folded."""
    from deeplearning4j_tpu import native_io as nio

    texts = ["café CAFÉ cafe (x)"]
    native = nio.count_vocab(texts, 1) if nio.available() else None
    # force the fallback path on a fresh module state
    import importlib

    saved = (nio._lib, nio._tried)
    try:
        nio._lib, nio._tried = None, True
        fallback = nio.count_vocab(texts, 1)
    finally:
        nio._lib, nio._tried = saved
    if native is not None:
        assert native[0] == fallback[0]
        assert native[1].tolist() == fallback[1].tolist()
        assert native[2] == fallback[2]
    assert "café" in fallback[0]


def test_prefetcher_epoch_label_at_exact_boundary():
    """n divisible by batch: every batch is labeled with the epoch its rows
    actually came from (native and fallback agree on the convention)."""
    feats = np.arange(40 * 2, dtype=np.uint8).reshape(40, 2)
    labels = np.zeros(40, np.uint8)
    loader = native_io.PrefetchingLoader(
        feats, labels, num_classes=2, batch_size=10, seed=0, depth=2
    )
    try:
        eps = [loader.next_batch()[2] for _ in range(8)]
        assert eps == [0, 0, 0, 0, 1, 1, 1, 1], eps
    finally:
        loader.close()
    import pytest as _pytest

    with _pytest.raises(RuntimeError):
        loader.next_batch()


def test_cli_serve_demo_observability_smoke(tmp_path, capsys):
    """`serve --demo` end to end with every observability flag on: the
    port file publishes the bound addresses (--port 0), /v1/generate
    answers over HTTP, /metrics serves Prometheus text on the main port
    AND the sidecar, the shutdown path writes a loadable Chrome-trace
    JSON, and --log-json emits req_id-correlated JSON lines."""
    import json
    import logging
    import threading
    import time
    import urllib.request

    from deeplearning4j_tpu.cli import main

    port_file = tmp_path / "ports.json"
    trace_out = tmp_path / "trace.json"
    rc = {}

    def run():
        rc["code"] = main([
            "serve", "--demo", "--port", "0",
            "--d-model", "32", "--n-layers", "1", "--n-heads", "2",
            "--seq-len", "32", "--slots", "2", "--decode-horizon", "1",
            "--temperature", "0", "--run-seconds", "12", "--drain-s", "5",
            "--port-file", str(port_file),
            "--trace-out", str(trace_out),
            "--log-json",
            "--metrics-port", "0",
            "--profile-dir", str(tmp_path / "prof"),
        ])

    t = threading.Thread(target=run, daemon=True)
    t.start()
    try:
        deadline = time.monotonic() + 30
        while not port_file.exists() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert port_file.exists(), "serve never published its port file"
        ports = json.loads(port_file.read_text())
        base = f"http://{ports['host']}:{ports['port']}"
        side = f"http://{ports['host']}:{ports['metrics_port']}"

        req = urllib.request.Request(
            f"{base}/v1/generate",
            data=json.dumps({"prompt": "hi", "max_new": 4}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=60) as r:
            out = json.loads(r.read())
        assert r.status == 200
        assert len(out["tokens"]) == 2 + 4
        assert "text" in out  # --demo is the byte-vocab model

        for b in (base, side):
            with urllib.request.urlopen(f"{b}/metrics", timeout=10) as r:
                prom = r.read().decode()
            assert "version=0.0.4" in r.headers.get("Content-Type")
            assert 'serve_requests_total{outcome="finished"} 1' in prom
        with urllib.request.urlopen(f"{base}/healthz", timeout=10) as r:
            assert r.status == 200
    finally:
        t.join(timeout=120)
        # --log-json attached a process-global handler; detach it
        pkg = logging.getLogger("deeplearning4j_tpu")
        for h in list(pkg.handlers):
            pkg.removeHandler(h)
        pkg.setLevel(logging.NOTSET)
    assert not t.is_alive(), "serve did not exit after --run-seconds"
    assert rc["code"] == 0

    doc = json.loads(trace_out.read_text())
    span_names = {
        e["name"] for e in doc["traceEvents"] if e["ph"] == "X"
    }
    assert {"step", "prefill", "decode", "queued"} <= span_names

    err = capsys.readouterr().err
    logged = [json.loads(ln) for ln in err.splitlines()
              if ln.strip().startswith("{")]
    admitted = [r for r in logged if r["event"] == "request_admitted"]
    assert admitted and "req_id" in admitted[0]
    completed = [r for r in logged if r["event"] == "request_completed"]
    assert completed and completed[0]["req_id"] == admitted[0]["req_id"]
    assert completed[0]["http"] == 200
