"""Memory-fused softmax CE: value/gradient parity with optax."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deeplearning4j_tpu.ops.fused_ce import cross_entropy_with_integer_labels


def _data(dtype, b=4, t=8, v=50):
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(0, 2.0, (b, t, v)), dtype)
    targets = jnp.asarray(rng.integers(0, v, (b, t)), jnp.int32)
    return logits, targets


def test_matches_optax_f32_value_and_grad():
    logits, targets = _data(jnp.float32)
    ce = cross_entropy_with_integer_labels(logits, targets)
    ref = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    np.testing.assert_allclose(np.asarray(ce), np.asarray(ref), rtol=1e-6)

    g = jax.grad(lambda l: cross_entropy_with_integer_labels(l, targets).mean())(logits)
    gr = jax.grad(
        lambda l: optax.softmax_cross_entropy_with_integer_labels(l, targets).mean()
    )(logits)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr), atol=1e-7)


def test_bf16_logits_f32_loss_and_bf16_cotangent():
    logits, targets = _data(jnp.bfloat16)
    ce = cross_entropy_with_integer_labels(logits, targets)
    assert ce.dtype == jnp.float32
    ref = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), targets
    )
    np.testing.assert_allclose(np.asarray(ce), np.asarray(ref), atol=1e-2)

    g = jax.grad(
        lambda l: cross_entropy_with_integer_labels(l, targets).mean()
    )(logits)
    assert g.dtype == jnp.bfloat16  # cotangent stays in storage dtype
    gr = jax.grad(
        lambda l: optax.softmax_cross_entropy_with_integer_labels(l, targets).mean()
    )(logits.astype(jnp.float32))
    np.testing.assert_allclose(
        np.asarray(g, np.float32), np.asarray(gr), atol=2e-4
    )


def test_jits_and_handles_extreme_logits():
    logits = jnp.asarray(
        [[[1e4, -1e4, 0.0], [-1e4, -1e4, -1e4]]], jnp.float32
    )
    targets = jnp.asarray([[0, 2]], jnp.int32)
    ce = jax.jit(cross_entropy_with_integer_labels)(logits, targets)
    assert np.isfinite(np.asarray(ce)).all()
    np.testing.assert_allclose(float(ce[0, 0]), 0.0, atol=1e-5)