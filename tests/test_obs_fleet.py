"""Fleet observability: distributed tracing, per-family device-time
attribution (MFU/MBU gauges), and the crash flight recorder.

The tentpole contract pinned here: one request through the router to a
replica produces, after ``trace-merge``, a single Perfetto document in
which the router's dispatch span is the PARENT of the replica's
admission span — verified structurally (the replica span's
``parent_span_id`` resolves to the router span's ``span_id`` on a
different process track, and a flow arrow links the two). Plus the
satellite contracts: MFU/MBU gauges stay in (0, 1], flight-recorder
dumps never contain prompt text, and the disabled paths cost nothing.
"""

import json
import threading

import numpy as np
import pytest

import jax

from deeplearning4j_tpu.models.transformer import (
    TransformerConfig,
    init_transformer,
)
from deeplearning4j_tpu.obs import (
    FlightRecorder,
    Tracer,
    format_traceparent,
    merge_traces,
    new_span_id,
    new_trace_id,
    parse_traceparent,
    redact,
)
from deeplearning4j_tpu.serving import (
    FaultInjector,
    Request,
    ServingEngine,
    ServingServer,
)
from deeplearning4j_tpu.serving.router import ReplicaRouter

CFG = TransformerConfig(
    vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64, max_len=32
)
_PARAMS = {}


def _params(seed=0):
    if seed not in _PARAMS:
        _PARAMS[seed] = init_transformer(jax.random.key(seed), CFG)
    return _PARAMS[seed]


# -- trace context --------------------------------------------------------


def test_traceparent_roundtrip():
    tid, sid = new_trace_id(), new_span_id()
    assert len(tid) == 32 and len(sid) == 16
    header = format_traceparent(tid, sid)
    assert parse_traceparent(header) == (tid, sid)
    # case-insensitive per spec, surrounding whitespace tolerated
    assert parse_traceparent(" " + header.upper() + " ") == (tid, sid)


@pytest.mark.parametrize("bad", [
    None, "", "junk", "00-" + "g" * 32 + "-" + "1" * 16 + "-01",
    "00-" + "0" * 32 + "-" + "1" * 16 + "-01",  # all-zero trace id
    "00-" + "a" * 32 + "-" + "0" * 16 + "-01",  # all-zero span id
    "00-" + "a" * 31 + "-" + "1" * 16 + "-01",  # short trace id
])
def test_traceparent_rejects_invalid(bad):
    assert parse_traceparent(bad) is None


# -- cross-process merge --------------------------------------------------


def _spans(doc):
    return [e for e in doc["traceEvents"] if e.get("ph") == "X"]


def test_merge_traces_synthetic_structure():
    """Three synthetic per-process exports merge into one document:
    one pid per input, process_name metadata preserved, timestamps
    rebased onto a shared origin, and flow arrows synthesized for
    exactly the cross-process parent links."""
    trace_id = new_trace_id()
    router = Tracer(process_name="router")
    d1, d2 = new_span_id(), new_span_id()
    router.span("router", "dispatch", router.now(), 0.001,
                trace_id=trace_id, span_id=d1)
    router.span("router", "dispatch", router.now(), 0.001,
                trace_id=trace_id, span_id=d2)
    reps = []
    for i, parent in enumerate((d1, d2)):
        t = Tracer(process_name=f"serve-{i}")
        child = new_span_id()
        t.span("slot-0", "prefill", t.now(), 0.002, trace_id=trace_id,
               span_id=child, parent_span_id=parent)
        # in-process child: nesting shows it, no arrow synthesized
        t.span("slot-0", "decode", t.now(), 0.001, trace_id=trace_id,
               span_id=new_span_id(), parent_span_id=child)
        reps.append(t)

    merged = merge_traces(
        [router.chrome_trace()] + [t.chrome_trace() for t in reps])
    evs = merged["traceEvents"]
    pids = {e["pid"] for e in evs}
    assert len(pids) == 3
    names = {e["args"]["name"] for e in evs
             if e.get("name") == "process_name"}
    assert names == {"router", "serve-0", "serve-1"}
    assert all(e["ts"] >= 0 for e in evs if e.get("ph") == "X")

    starts = [e for e in evs if e.get("ph") == "s"]
    finishes = [e for e in evs if e.get("ph") == "f"]
    # two cross-process links (one per replica), NOT the in-process one
    assert len(starts) == len(finishes) == 2
    router_pid = next(e["pid"] for e in evs
                      if e.get("name") == "process_name"
                      and e["args"]["name"] == "router")
    for s in starts:
        assert s["pid"] == router_pid
        f = next(f for f in finishes if f["id"] == s["id"])
        assert f["pid"] != router_pid
        assert f["bp"] == "e"
    # the merged doc is valid JSON end to end
    json.dumps(merged)


def _post(addr, body, headers=None, timeout=60):
    import http.client

    conn = http.client.HTTPConnection(*addr, timeout=timeout)
    try:
        h = {"Content-Type": "application/json"}
        h.update(headers or {})
        conn.request("POST", "/v1/generate",
                     body=json.dumps(body).encode(), headers=h)
        r = conn.getresponse()
        return r.status, json.loads(r.read()), r.getheader("X-Served-By")
    finally:
        conn.close()


def _get_json(addr, path, timeout=10):
    import http.client

    conn = http.client.HTTPConnection(*addr, timeout=timeout)
    try:
        conn.request("GET", path)
        r = conn.getresponse()
        return r.status, json.loads(r.read())
    finally:
        conn.close()


def test_fleet_merged_trace_router_parents_admission():
    """The tentpole, live: router + 2 traced replicas over real HTTP.
    The merged trace has >= 3 process tracks, and every replica
    admission span's parent resolves to a router dispatch span on the
    router's track (cross-process), with a flow arrow between them."""
    servers, tracers = [], []
    for i in range(2):
        tr = Tracer(process_name=f"serve-{i}")
        eng = ServingEngine(
            CFG, _params(), n_slots=2, temperature=0.0,
            decode_horizon=2, tracer=tr,
            retry_backoff_s=0.001, max_backoff_s=0.004,
        )
        tracers.append(tr)
        servers.append(ServingServer(eng, port=0).start())
    rtr_tracer = Tracer(process_name="router")
    router = ReplicaRouter(
        [s.address for s in servers], health_interval_s=0.1,
        tracer=rtr_tracer,
    ).start()
    caller_trace = new_trace_id()
    try:
        rng = np.random.default_rng(3)
        for i in range(4):
            prompt = [int(t) for t in rng.integers(1, 60, 5 + i)]
            headers = None
            if i == 0:  # one request arrives with upstream context
                headers = {"traceparent": format_traceparent(
                    caller_trace, new_span_id())}
            status, body, served_by = _post(
                router.address, {"prompt": prompt, "max_new": 3},
                headers=headers)
            assert status == 200, body
            assert served_by is not None
    finally:
        router.stop()
        for s in servers:
            s.stop()

    docs = [rtr_tracer.chrome_trace()] + [
        t.chrome_trace() for t in tracers]
    merged = merge_traces(docs)
    evs = merged["traceEvents"]
    assert len({e["pid"] for e in evs}) >= 3

    dispatches = {
        e["args"]["span_id"]: e for e in evs
        if e.get("ph") == "X" and e["name"] == "dispatch"
        and "span_id" in e.get("args", {})
    }
    admissions = [
        e for e in evs
        if e.get("ph") == "X" and e["name"] == "prefill"
        and e.get("args", {}).get("parent_span_id")
    ]
    assert len(dispatches) == 4
    assert len(admissions) == 4
    for adm in admissions:
        parent = dispatches[adm["args"]["parent_span_id"]]
        assert parent["pid"] != adm["pid"]  # cross-process link
        assert parent["args"]["trace_id"] == adm["args"]["trace_id"]
    # the upstream traceparent was adopted end to end
    assert any(a["args"]["trace_id"] == caller_trace
               for a in admissions)
    # every resolved link got its flow arrow
    assert sum(1 for e in evs if e.get("ph") == "s") == 4
    assert sum(1 for e in evs if e.get("ph") == "f") == 4


# -- MFU / MBU attribution ------------------------------------------------


def _drive(engine, n=3, seed=11, max_new=5):
    rng = np.random.default_rng(seed)
    reqs = []
    for i in range(n):
        r = Request(
            prompt=rng.integers(1, CFG.vocab_size,
                                (int(rng.integers(4, 10)),))
            .astype(np.int32),
            max_new=max_new, done=threading.Event(),
        )
        engine.submit(r)
        reqs.append(r)
    for _ in range(500):
        if not engine.step() and all(r.done.is_set() for r in reqs):
            break
    return reqs


def test_mfu_mbu_gauges_in_unit_interval():
    """Attribution prices measured wall seconds against the static
    audit budgets: every emitted family gets seconds + dispatch
    counters, and the derived MFU/MBU gauges land in (0, 1] — the
    clamp's upper bound and physics' lower one."""
    engine = ServingEngine(CFG, _params(), n_slots=2, temperature=0.0,
                           decode_horizon=2)
    _drive(engine)
    assert engine.metrics.program_seconds, "no families attributed"
    assert set(engine.metrics.program_dispatches) == set(
        engine.metrics.program_seconds)
    assert "step" in engine.metrics.program_seconds
    assert all(s > 0 for s in engine.metrics.program_seconds.values())

    text = engine.metrics.render_prometheus()
    import re

    for fam in engine.metrics.program_seconds:
        assert f'serve_program_seconds_total{{family="{fam}"}}' in text
        assert f'serve_program_dispatches_total{{family="{fam}"}}' in text
    vals = [float(v) for v in re.findall(
        r'serve_m[fb]u\{family="[^"]+"\} ([0-9.e+-]+)', text)]
    assert vals, "no serve_mfu/serve_mbu samples rendered"
    assert all(0.0 < v <= 1.0 for v in vals), vals


def test_attribution_flush_is_prefix_ordered():
    """Entries flush only once a later horizon readback proves them
    complete; after a full drain the pending list is empty (nothing
    leaks) and dispatch counts match the metrics' dispatch counters."""
    engine = ServingEngine(CFG, _params(), n_slots=2, temperature=0.0,
                           decode_horizon=2)
    _drive(engine)
    assert engine._pending_attr == []
    md = engine.metrics.program_dispatches
    assert md.get("step", 0) >= 1
    assert md.get("prefill", 0) + md.get("batch_prefill", 0) >= 1


def test_attribution_disabled_records_nothing():
    engine = ServingEngine(CFG, _params(), n_slots=2, temperature=0.0,
                           decode_horizon=2, attribution=False)
    _drive(engine, n=2)
    assert engine.metrics.program_seconds == {}
    assert engine.metrics.program_dispatches == {}
    assert engine._pending_attr == []
    # and the render carries no per-family series at all
    text = engine.metrics.render_prometheus()
    assert 'serve_mfu{' not in text
    assert 'serve_program_seconds_total{' not in text


def test_recovery_replay_not_attributed():
    """Crash-recovery replay re-dispatches prefills and steps that
    already ran; pricing them again would double-count device time, so
    recover() suspends attribution for its whole replay."""
    inj = FaultInjector().plan("step", at=2, kind="crash")
    engine = ServingEngine(
        CFG, _params(), n_slots=2, temperature=0.0, decode_horizon=2,
        faults=inj, retry_backoff_s=0.001, max_backoff_s=0.004,
    )
    rng = np.random.default_rng(5)
    reqs = []
    for _ in range(2):
        r = Request(
            prompt=rng.integers(1, 60, (6,)).astype(np.int32),
            max_new=6, done=threading.Event(),
        )
        engine.submit(r)
        reqs.append(r)
    engine.run()
    assert engine.metrics.n_restarts == 1
    # attribution survived the crash (re-armed after recovery) and the
    # books balance: fewer attributed step dispatches than total step
    # calls would imply had the replay been counted too
    assert engine._attr_suspend == 0
    assert engine._pending_attr == []
    assert engine.metrics.program_dispatches.get("step", 0) >= 1


# -- flight recorder ------------------------------------------------------


def test_flight_recorder_ring_and_redaction():
    fr = FlightRecorder(capacity=4)
    for i in range(6):
        fr.record("dispatch", k=i, prompt=[1, 2, 3],
                  text="secret prompt")
    assert fr.n_events == 4  # ring bounded
    assert fr.dropped == 2
    bundle = fr.dump("test")
    raw = json.dumps(bundle)
    assert "secret prompt" not in raw
    assert "[redacted] len=3" in raw  # sized placeholder for the list
    assert bundle["n_events"] == 4 and bundle["n_dropped"] == 2


def test_redact_nested_structures():
    obj = {"a": {"tokens": (1, 2), "deep": [{"prompt": "xyz"}]},
           "keep": 7}
    out = redact(obj)
    assert out["keep"] == 7
    assert out["a"]["tokens"] == "[redacted] len=2"
    assert out["a"]["deep"][0]["prompt"] == "[redacted] len=3"


@pytest.mark.chaos
def test_flight_dump_on_chaos_crash_has_no_prompt_text(tmp_path):
    """A chaos-marker crash inside a supervised server produces a
    flight bundle on disk whose events cover the crash — with every
    prompt field redacted."""
    inj = FaultInjector().plan("step", at=1, kind="crash")
    engine = ServingEngine(
        CFG, _params(), n_slots=2, temperature=0.0, decode_horizon=2,
        faults=inj, retry_backoff_s=0.001, max_backoff_s=0.004,
    )
    server = ServingServer(engine, port=0,
                           flight_dir=str(tmp_path)).start()
    try:
        marker = [7, 13, 42, 19, 23, 29]
        status, body, _ = _post(
            server.address, {"prompt": marker, "max_new": 4})
        assert status == 200, body
    finally:
        server.stop()
    bundles = list(tmp_path.glob("flight-*engine_crash*.json"))
    assert bundles, list(tmp_path.iterdir())
    doc = json.loads(bundles[0].read_text())
    assert doc["reason"] == "engine_crash"
    kinds = {e["kind"] for e in doc["events"]}
    assert {"admit", "dispatch", "fault"} <= kinds
    raw = json.dumps(doc)
    assert "[7, 13, 42" not in raw  # prompt tokens never leave
    assert all("prompt" not in e or str(e["prompt"]).startswith(
        "[redacted]") for e in doc["events"])


def test_debug_dump_endpoints_server_and_router():
    engine = ServingEngine(CFG, _params(), n_slots=2, temperature=0.0,
                           decode_horizon=2)
    server = ServingServer(engine, port=0).start()
    router = ReplicaRouter([server.address],
                           health_interval_s=0.1).start()
    try:
        status, body, _ = _post(
            router.address, {"prompt": [3, 5, 7, 11], "max_new": 2})
        assert status == 200, body
        code, dump = _get_json(server.address, "/debug/dump")
        assert code == 200
        assert dump["reason"] == "debug_dump"
        assert {"admit", "dispatch"} <= {e["kind"] for e in dump["events"]}
        assert dump["metrics"]["n_finished"] >= 1
        code, rdump = _get_json(router.address, "/debug/dump")
        assert code == 200
        assert any(e["kind"] == "dispatch" for e in rdump["events"])
        assert rdump["replicas"]  # routing state rides along
    finally:
        router.stop()
        server.stop()


# -- disabled paths cost nothing ------------------------------------------


def test_disabled_flight_recorder_records_nothing():
    fr = FlightRecorder(enabled=False)
    for _ in range(10):
        fr.record("dispatch", k=1)
    assert fr.n_events == 0 and fr.dropped == 0
    # a dump still works (empty postmortem, never throws)
    assert fr.dump("test")["events"] == []


def test_disabled_tracer_and_attribution_zero_overhead():
    """The acceptance guard: with tracing and attribution off and the
    flight recorder off, serving records no observability events at
    all — and the token streams are byte-identical to a fully
    instrumented engine's."""
    flight_off = FlightRecorder(enabled=False)
    eng_off = ServingEngine(
        CFG, _params(), n_slots=2, temperature=0.0, decode_horizon=2,
        tracer=Tracer(enabled=False), flight=flight_off,
        attribution=False,
    )
    reqs_off = _drive(eng_off)
    assert eng_off.tracer.n_events == 0
    assert flight_off.n_events == 0
    assert eng_off.metrics.program_seconds == {}

    eng_on = ServingEngine(
        CFG, _params(), n_slots=2, temperature=0.0, decode_horizon=2,
        tracer=Tracer(enabled=True),
    )
    reqs_on = _drive(eng_on)
    assert eng_on.tracer.n_events > 0
    assert eng_on.flight.n_events > 0
    for a, b in zip(reqs_off, reqs_on):
        np.testing.assert_array_equal(
            eng_off.pop_result(a.id), eng_on.pop_result(b.id))
