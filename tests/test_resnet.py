"""ResNet + BatchNorm (beyond-parity modern CNN family)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.models.resnet import (
    ResNetConfig, init_resnet, resnet_apply, resnet_train_step,
)

CFG = ResNetConfig(num_classes=4, blocks_per_stage=1,
                   stage_channels=(8, 16))


def _data(n=16, hw=16, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, hw, hw, 3)).astype(np.float32)
    labels = rng.integers(0, 4, n)
    # make the task learnable: shift each image by its class
    x += labels[:, None, None, None] * 0.7
    y = np.eye(4, dtype=np.float32)[labels]
    return jnp.asarray(x), jnp.asarray(y)


def test_forward_shapes_and_state_update():
    params, state = init_resnet(jax.random.key(0), CFG)
    x, _ = _data()
    logits, new_state = resnet_apply(CFG, train=True)(params, state, x)
    assert logits.shape == (16, 4)
    # train mode rolls the running statistics
    assert not np.allclose(
        np.asarray(new_state["stem"]["mean"]),
        np.asarray(state["stem"]["mean"]),
    )
    # eval mode leaves them untouched and is deterministic
    l1, s1 = resnet_apply(CFG, train=False)(params, state, x)
    l2, s2 = resnet_apply(CFG, train=False)(params, state, x)
    np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))
    np.testing.assert_array_equal(
        np.asarray(s1["stem"]["mean"]), np.asarray(state["stem"]["mean"])
    )


def test_trains_and_eval_mode_classifies():
    step, init = resnet_train_step(CFG)
    params, state, opt_state = init(jax.random.key(1))
    x, y = _data(n=32, seed=1)
    losses = []
    for _ in range(40):
        params, state, opt_state, loss = step(params, state, opt_state, x, y)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.5, losses[::10]
    # eval-mode accuracy on the training batch after fitting
    logits, _ = resnet_apply(CFG, train=False)(params, state, x)
    acc = float(
        (jnp.argmax(logits, -1) == jnp.argmax(y, -1)).mean()
    )
    assert acc >= 0.75, acc


def test_sync_bn_shard_map_matches_full_batch(devices):
    """Per-replica BN with axis_name pmean == full-batch BN: the sync-BN
    contract for shard_map/pmap regimes (each replica sees only its
    batch shard; the moments are averaged over the dp axis)."""
    from functools import partial

    from deeplearning4j_tpu.utils.compat import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from deeplearning4j_tpu.models.resnet import _batch_norm

    mesh = Mesh(np.array(devices[:8]), ("data",))
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(32, 4, 4, 6)).astype(np.float32))
    p = {"scale": jnp.asarray(rng.normal(size=(6,)).astype(np.float32)),
         "bias": jnp.asarray(rng.normal(size=(6,)).astype(np.float32))}
    s = {"mean": jnp.zeros((6,)), "var": jnp.ones((6,))}

    y_ref, s_ref = _batch_norm(x, p, s, True, 0.9, 1e-5)

    @partial(
        shard_map, mesh=mesh,
        in_specs=(P("data"), P(), P()),
        out_specs=(P("data"), P()),
        check_vma=False,
    )
    def sharded_bn(xs, p, s):
        return _batch_norm(xs, p, s, True, 0.9, 1e-5, axis_name="data")

    y, s_new = sharded_bn(x, p, s)
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(y_ref), rtol=1e-5, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(s_new["mean"]), np.asarray(s_ref["mean"]),
        rtol=1e-5, atol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(s_new["var"]), np.asarray(s_ref["var"]),
        rtol=1e-5, atol=1e-6,
    )


def test_pjit_batch_norm_is_sync(devices):
    """Under jit with a dp-sharded batch, the BN reductions are GLOBAL
    (XLA inserts the collectives): the whole-model train step over an
    8-device-sharded batch matches the single-device run — the property
    'sync-BN over the dp axis' reduces to under pjit."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    step, init = resnet_train_step(CFG)
    params, state, opt_state = init(jax.random.key(4))
    x, y = _data(n=32, seed=4)

    p2, s2, o2 = jax.tree.map(jnp.copy, (params, state, opt_state))
    mesh = Mesh(np.array(devices[:8]), ("data",))
    xs = jax.device_put(x, NamedSharding(mesh, P("data")))
    ys = jax.device_put(y, NamedSharding(mesh, P("data")))

    _, state_1, _, loss_1 = step(params, state, opt_state, x, y)
    _, state_8, _, loss_8 = step(p2, s2, o2, xs, ys)
    np.testing.assert_allclose(
        float(loss_1), float(loss_8), rtol=1e-5, atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(state_8["stem"]["mean"]),
        np.asarray(state_1["stem"]["mean"]),
        rtol=1e-5, atol=1e-6,
    )


@pytest.mark.slow
def test_cifar_accuracy_acceptance():
    """Accuracy acceptance with a concrete bound, like the DBN-Iris
    gate: ResNet on the structured synthetic CIFAR task (the offline
    stand-in — zero-egress env), evaluated on a HELD-OUT split in eval
    mode (running BN statistics). The task has real signal (oriented
    gratings per class) under noise; a broken residual/BN/optimizer
    path fails the bound immediately."""
    import optax

    from deeplearning4j_tpu.models.alexnet import synthetic_cifar

    ds = synthetic_cifar(n=1536, seed=7)
    x = np.asarray(ds.features, np.float32).reshape(-1, 32, 32, 3)
    y = np.asarray(ds.labels, np.float32)
    x_tr, y_tr = jnp.asarray(x[:1024]), jnp.asarray(y[:1024])
    x_te, y_te = jnp.asarray(x[1024:]), jnp.asarray(y[1024:])

    cfg = ResNetConfig(num_classes=10, blocks_per_stage=1,
                       stage_channels=(8, 16, 32))
    step, init = resnet_train_step(
        cfg, optimizer=optax.sgd(0.05, momentum=0.9)
    )
    params, state, opt_state = init(jax.random.key(5))
    rng = np.random.default_rng(5)
    # held-out accuracy saturates at 1.0 by ~step 60 on this task
    # (measured); 70 keeps margin over the 0.85 gate at half the wall
    # time of the original 120
    for _ in range(70):
        idx = rng.integers(0, len(x_tr), 256)
        params, state, opt_state, loss = step(
            params, state, opt_state, x_tr[idx], y_tr[idx]
        )
    assert np.isfinite(float(loss))
    logits, _ = resnet_apply(cfg, train=False)(params, state, x_te)
    acc = float((jnp.argmax(logits, -1) == jnp.argmax(y_te, -1)).mean())
    assert acc >= 0.85, f"held-out accuracy {acc:.3f} below the 0.85 gate"


def test_projection_skips_only_on_channel_change():
    params, _ = init_resnet(jax.random.key(2), CFG)
    # first block of stage 0: in==out channels (stem matches stage 0)
    assert "proj" not in params["stages"][0][0]
    # first block of stage 1: 8 -> 16 channels needs the 1x1 projection
    assert "proj" in params["stages"][1][0]
    assert params["stages"][1][0]["proj"].shape == (1, 1, 8, 16)
