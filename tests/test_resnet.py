"""ResNet + BatchNorm (beyond-parity modern CNN family)."""

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.models.resnet import (
    ResNetConfig, init_resnet, resnet_apply, resnet_train_step,
)

CFG = ResNetConfig(num_classes=4, blocks_per_stage=1,
                   stage_channels=(8, 16))


def _data(n=16, hw=16, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, hw, hw, 3)).astype(np.float32)
    labels = rng.integers(0, 4, n)
    # make the task learnable: shift each image by its class
    x += labels[:, None, None, None] * 0.7
    y = np.eye(4, dtype=np.float32)[labels]
    return jnp.asarray(x), jnp.asarray(y)


def test_forward_shapes_and_state_update():
    params, state = init_resnet(jax.random.key(0), CFG)
    x, _ = _data()
    logits, new_state = resnet_apply(CFG, train=True)(params, state, x)
    assert logits.shape == (16, 4)
    # train mode rolls the running statistics
    assert not np.allclose(
        np.asarray(new_state["stem"]["mean"]),
        np.asarray(state["stem"]["mean"]),
    )
    # eval mode leaves them untouched and is deterministic
    l1, s1 = resnet_apply(CFG, train=False)(params, state, x)
    l2, s2 = resnet_apply(CFG, train=False)(params, state, x)
    np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))
    np.testing.assert_array_equal(
        np.asarray(s1["stem"]["mean"]), np.asarray(state["stem"]["mean"])
    )


def test_trains_and_eval_mode_classifies():
    step, init = resnet_train_step(CFG)
    params, state, opt_state = init(jax.random.key(1))
    x, y = _data(n=32, seed=1)
    losses = []
    for _ in range(40):
        params, state, opt_state, loss = step(params, state, opt_state, x, y)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.5, losses[::10]
    # eval-mode accuracy on the training batch after fitting
    logits, _ = resnet_apply(CFG, train=False)(params, state, x)
    acc = float(
        (jnp.argmax(logits, -1) == jnp.argmax(y, -1)).mean()
    )
    assert acc >= 0.75, acc


def test_projection_skips_only_on_channel_change():
    params, _ = init_resnet(jax.random.key(2), CFG)
    # first block of stage 0: in==out channels (stem matches stage 0)
    assert "proj" not in params["stages"][0][0]
    # first block of stage 1: 8 -> 16 channels needs the 1x1 projection
    assert "proj" in params["stages"][1][0]
    assert params["stages"][1][0]["proj"].shape == (1, 1, 8, 16)
