"""Porter stemmer (≙ StemmerAnnotator's Snowball stemming)."""

import pytest

from deeplearning4j_tpu.nlp.stemmer import PorterStemmer, porter_stem
from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizer, lowercase

# full-pipeline outputs of the original Porter (1980) algorithm
VECTORS = {
    "caresses": "caress", "ponies": "poni", "ties": "ti",
    "caress": "caress", "cats": "cat", "feed": "feed", "agreed": "agre",
    "plastered": "plaster", "motoring": "motor", "sing": "sing",
    "happy": "happi", "sky": "sky", "generalizations": "gener",
    "oscillators": "oscil", "university": "univers",
    "universities": "univers", "running": "run", "runner": "runner",
    "easily": "easili", "national": "nation", "nationality": "nation",
    "determination": "determin", "conditional": "condit",
    "effective": "effect", "hopping": "hop", "tanned": "tan",
    "falling": "fall", "hissing": "hiss", "filing": "file",
    "adjustable": "adjust", "replacement": "replac", "adoption": "adopt",
    "argue": "argu", "argued": "argu", "arguing": "argu",
}


def test_porter_canonical_vectors():
    for word, want in VECTORS.items():
        assert porter_stem(word) == want, (word, porter_stem(word), want)


def test_porter_matches_nltk_original_algorithm():
    """Oracle cross-check against the reference implementation of the
    original algorithm (skipped when nltk is absent)."""
    nltk_stem = pytest.importorskip("nltk.stem.porter")
    ref = nltk_stem.PorterStemmer(mode="ORIGINAL_ALGORITHM")
    words = (
        "the quick brown foxes were jumping over lazily sleeping dogs "
        "relational conditional rational operations digitizer radically "
        "hopefulness electrical revival allowance inference airliner "
        "gyroscopic irritant dependent homologous communism activated "
        "probate cease controlling rolled troubles troubling sensible "
        "sensibility capabilities derivational derived derive derives"
    ).split()
    for w in words:
        assert porter_stem(w) == ref.stem(w), w


def test_stemmer_composes_as_tokenizer_preprocessor():
    tok = DefaultTokenizer(preprocessors=(lowercase, PorterStemmer()))
    assert tok.tokens("The Runners were RUNNING easily!") == [
        "the", "runner", "were", "run", "easili",
    ]
