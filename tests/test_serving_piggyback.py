"""Chunked-prefill piggyback suite (PR 18).

The load-bearing property is the house parity bar, one more axis: an
engine that splits long prompts into pow2 chunks and rides them along
with decode dispatches (``piggyback=True`` — the last budgeted chunk
FUSED into the decode step program itself) streams BYTE-IDENTICAL
tokens to the blocking-admission engine — greedy AND sampled, through
the adaptive horizon, prefix-cache partial hits (only the uncached
suffix is piggybacked), paged block tables, fault-injected crash
recovery mid-prefill, and TP=2. That holds by construction (the fused
``piggyback_step`` program is the decode substep envelope followed by
the exact chunk-prefill leg the blocking path runs, and the admission
key chain is pre-split in blocking order) and is enforced at engine
construction by a bitwise parity probe persisted through
``ProbeCache``.

The second contract is accounting: piggybacked chunk tokens are
charged to the owning tenant's DRR deficit at execution time (the
pop-time charge is credited back at deferral), so a tenant cannot
smuggle free prefill past the fair scheduler by sending long prompts.
"""

import os

import numpy as np
import pytest

import jax

from deeplearning4j_tpu.models.transformer import (
    TransformerConfig,
    init_transformer,
)
from deeplearning4j_tpu.serving import (
    FaultInjector,
    Request,
    RequestScheduler,
    ServingEngine,
)

pytestmark = pytest.mark.piggyback

needs_2_devices = pytest.mark.skipif(
    len(jax.devices()) < 2, reason="needs >= 2 devices for TP/sharding"
)

CFG = TransformerConfig(
    vocab_size=128, d_model=64, n_heads=4, n_kv_heads=2, n_layers=2,
    d_ff=128, max_len=64, rope=True, decode_kernel=False,
)
_PARAMS = {}


def _params(cfg=CFG, seed=0):
    key = (id(cfg), seed)
    if key not in _PARAMS:
        _PARAMS[key] = init_transformer(jax.random.key(seed), cfg)
    return _PARAMS[key]


def _engine(piggyback=False, n_slots=4, cfg=CFG, **kw):
    kw.setdefault("temperature", 0.0)
    kw.setdefault("max_total", 64)
    kw.setdefault("decode_horizon", 2)
    kw.setdefault("adaptive_horizon", True)
    # small bucket cap so mid-size prompts decompose into several
    # chunks (and thus actually exercise deferral + the fused leg)
    kw.setdefault("prefill_max_bucket", 8)
    return ServingEngine(
        cfg, _params(cfg), n_slots=n_slots,
        piggyback=piggyback,
        retry_backoff_s=0.001, max_backoff_s=0.004, **kw,
    )


def _piggy(**kw):
    eng = _engine(piggyback=True, **kw)
    assert eng._piggyback, "piggyback engine silently fell back"
    return eng


def _requests(n=8, seed=1, shared_frac=0.5):
    """Mixed trace: short prompts (blocking path) + long prompts that
    exceed the 8-token bucket cap (piggyback path), half sharing a
    24-token prefix so partial hits leave an uncached suffix."""
    rng = np.random.default_rng(seed)
    shared = ((1 + np.arange(24)) % 127).astype(np.int32)
    reqs = []
    for i in range(n):
        ln = int(rng.integers(3, 40)) if i % 3 else 36
        if i % 2 and i < int(2 * shared_frac * n):
            p = np.concatenate(
                [shared, ((7 + np.arange(ln)) % 127).astype(np.int32)]
            )[:58]
        else:
            p = ((1 + np.arange(ln)) % 127).astype(np.int32)
        reqs.append(Request(id=f"r{i}", prompt=p, max_new=6))
    return reqs


def _clone(reqs):
    return [Request(id=r.id, prompt=np.asarray(r.prompt).copy(),
                    max_new=r.max_new, tenant_id=r.tenant_id)
            for r in reqs]


def _run(engine, reqs, **run_kw):
    for r in reqs:
        engine.submit(r)
    engine.run(**run_kw)
    return {r.id: np.asarray(engine.results[r.id]) for r in reqs}


def _assert_same(a, b):
    assert a.keys() == b.keys()
    for k in a:
        np.testing.assert_array_equal(a[k], b[k])


# -- tentpole: piggyback on/off byte parity ------------------------------


@pytest.mark.parametrize("temperature", [0.0, 0.9])
def test_piggyback_on_off_byte_parity(temperature):
    """Adaptive-horizon trace, greedy and sampled: byte-identical
    streams, and the piggyback engine actually executed chunks
    (non-vacuity)."""
    reqs = _requests()
    ref = _run(_engine(temperature=temperature), _clone(reqs))
    eng = _piggy(temperature=temperature)
    got = _run(eng, _clone(reqs))
    _assert_same(ref, got)
    assert eng.metrics.n_prefill_chunks > 0, "no chunk ever piggybacked"
    assert eng.metrics.prefill_chunk_tokens > 0


@pytest.mark.parametrize("temperature", [0.0, 0.9])
def test_piggyback_prefix_partial_hit_parity(temperature):
    """Prefix-cache partial hits: only the uncached suffix is
    piggybacked, and the streams still match the blocking engine with
    the same cache."""
    reqs = _requests()
    ref = _run(_engine(temperature=temperature, prefix_cache=True),
               _clone(reqs))
    eng = _piggy(temperature=temperature, prefix_cache=True)
    got = _run(eng, _clone(reqs))
    _assert_same(ref, got)
    assert eng.metrics.n_prefix_hits_partial > 0, "no partial hit fired"
    assert eng.metrics.n_prefill_chunks > 0


@pytest.mark.parametrize("temperature", [0.0, 0.9])
def test_piggyback_paged_parity(temperature):
    """Paged pool underneath: pending slots hold only private blocks
    until completion (aliasing deferred), and bytes still match."""
    kw = dict(temperature=temperature, paged=True, block_size=8,
              prefix_cache=True)
    reqs = _requests()
    ref = _run(_engine(**kw), _clone(reqs))
    eng = _piggy(**kw)
    assert eng._paged, "paged engine silently fell back to slab"
    got = _run(eng, _clone(reqs))
    _assert_same(ref, got)
    assert eng.metrics.n_prefill_chunks > 0


@pytest.mark.parametrize("temperature", [0.0, 0.9])
@pytest.mark.parametrize("crash_at", [1, 3, 5])
def test_piggyback_crash_mid_prefill_parity(temperature, crash_at):
    """Seeded crash while prefills are pending: recovery requeues the
    pending records (releasing their slots and pinned segments) and the
    replay still streams the blocking engine's bytes."""
    reqs = _requests()
    ref = _run(_engine(temperature=temperature), _clone(reqs))
    faults = FaultInjector().plan("step", crash_at, "crash")
    eng = _piggy(temperature=temperature, faults=faults)
    got = _run(eng, _clone(reqs), max_restarts=5)
    _assert_same(ref, got)
    assert eng.metrics.n_restarts >= 1, "crash never fired"
    assert eng.metrics.n_prefill_chunks > 0


@needs_2_devices
@pytest.mark.parametrize("temperature", [0.0, 0.9])
def test_piggyback_tp2_parity(temperature):
    """TP=2 piggyback vs single-chip blocking: same bytes (the fused
    piggyback program shards like step + chunk — its spec declares
    K + 1 substeps)."""
    reqs = _requests()
    ref = _run(_engine(temperature=temperature), _clone(reqs))
    eng = _piggy(temperature=temperature, tp=2)
    assert eng.tp == 2, "TP parity probe fell back to tp=1"
    got = _run(eng, _clone(reqs))
    _assert_same(ref, got)
    assert eng.metrics.n_prefill_chunks > 0


# -- compile surface -----------------------------------------------------


def test_piggyback_compile_surface_bounded():
    """The piggyback family is bounded to the pow2 chunk grid x the
    engine's horizon set {1, K}: every compiled (bucket, K) key lies on
    that grid, and the live engine surface is a subset of the audited
    expected surface."""
    from deeplearning4j_tpu.analysis.programs import (
        ServingGeometry,
        expected_surface,
        live_engine_families,
    )

    eng = _piggy()
    _run(eng, _requests())
    keys = set(eng._piggyback_fns)
    assert keys, "no piggyback program ever compiled"
    buckets = {b for b, _ in keys}
    horizons = {k for _, k in keys}
    assert all(b & (b - 1) == 0 for b in buckets), buckets
    assert all(b <= eng._max_bucket for b in buckets), buckets
    assert horizons <= {1, eng.decode_horizon}, horizons

    geom = ServingGeometry(
        n_slots=eng.n_slots, max_total=eng.max_total,
        temperature=eng.temperature, top_k=eng.top_k,
        approx_top_k=eng.approx_top_k,
        decode_horizon=eng.decode_horizon, adaptive_horizon=True,
        prefill_max_bucket=eng._max_bucket,
    )
    exp = expected_surface(CFG, geom)
    live = live_engine_families(eng)
    assert live["piggyback_step"] <= exp["piggyback_step"]
    assert live["paged_piggyback_step"] == set()


# -- DRR accounting (satellite bugfix) -----------------------------------


def test_scheduler_adjust_deficit_and_carry():
    """adjust_deficit credits a present tenant's deficit directly and
    banks adjustments for absent tenants in the carry dict, applied on
    re-entry — the mechanism that moves the prefill charge from pop
    time to execution time."""
    sched = RequestScheduler()
    r1 = Request(id="a", prompt=np.arange(4, dtype=np.int32), max_new=2,
                 tenant_id="t1")
    sched.submit(r1)
    drr = sched._drr[r1.priority]
    assert "t1" in drr["deficit"]
    before = drr["deficit"]["t1"]
    sched.adjust_deficit(r1, 5.0)
    assert drr["deficit"]["t1"] == before + 5.0
    # absent tenant: adjustment banks in carry, lands on re-entry
    r2 = Request(id="b", prompt=np.arange(4, dtype=np.int32), max_new=2,
                 tenant_id="t2")
    sched.adjust_deficit(r2, -3.0)
    assert drr["carry"]["t2"] == -3.0
    sched.submit(r2)
    assert drr["deficit"]["t2"] == -3.0
    assert "t2" not in drr["carry"]


def test_piggyback_charges_owner_tenant():
    """Piggybacked chunk tokens land on the owning tenant's deficit:
    after a full run the net DRR charge for a long-prompt tenant equals
    the blocking engine's (pop-time) charge — deferral credit and
    per-chunk debits cancel exactly."""
    charges = {}
    for pb in (False, True):
        eng = _engine(piggyback=pb)
        reqs = [Request(id=f"x{i}", prompt=np.arange(1, 37, dtype=np.int32),
                        max_new=4, tenant_id="long") for i in range(2)]
        _run(eng, reqs)
        if pb:
            assert eng.metrics.n_prefill_chunks > 0
        drr = eng.scheduler._drr[reqs[0].priority]
        charges[pb] = drr["deficit"].get("long", 0.0) + \
            drr["carry"].get("long", 0.0)
    assert charges[True] == pytest.approx(charges[False])


# -- probe caching -------------------------------------------------------


def test_piggyback_parity_probe_cached_across_engines(tmp_path):
    """The construction-time piggyback-parity verdict persists through
    ProbeCache: a second engine with the same geometry constructs with
    ZERO probe dispatches."""
    path = str(tmp_path / "probes.json")
    e1 = _piggy(probe_cache=path)
    assert "piggyback_parity" in e1.probes_run
    assert os.path.exists(path)
    e2 = _piggy(probe_cache=path)
    assert e2._piggyback
    assert "piggyback_parity" in e2.probes_from_cache
    assert e2.probes_run == []
