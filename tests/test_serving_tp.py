"""Tensor-parallel serving + replica-router suite (PR 6).

The load-bearing property extends the house parity bar one more axis:
sharding the fused decode program and the KV slot pool over a device
mesh must be invisible in the bytes. A TP=2 engine's token streams —
greedy AND sampled, through batched admission, fused horizons, and
crash-recovery replay — are asserted identical to the single-chip
engine's. That holds by construction of the exact-TP layout (column
projections shard; row projections stay replicated behind a forced
all-gather, so every floating-point reduction keeps single-chip flop
order) and is enforced at engine construction by a bitwise parity
probe that falls back to tp=1 on any mismatch.

The router suite pins the fleet-level contracts: prefix-affinity
dispatch (shared-prefix prompts pin to one replica's cache),
least-loaded spread otherwise, and per-replica fault isolation — one
replica crash-recovering (or dying outright) never fails requests on
the other.

Multi-device cases skip cleanly when the host exposes a single device
(conftest forces 8 virtual CPU devices, so CI always runs them).
"""

import http.client
import json
import threading

import numpy as np
import pytest

import jax

from deeplearning4j_tpu.models.transformer import (
    TransformerConfig,
    init_transformer,
)
from deeplearning4j_tpu.serving import (
    FaultInjector,
    KVSlotPool,
    PrefixCache,
    Request,
    ServingEngine,
    ServingServer,
)
from deeplearning4j_tpu.serving.probe_cache import ProbeCache, probe_key
from deeplearning4j_tpu.serving.router import PrefixShadow, ReplicaRouter

pytestmark = pytest.mark.tp_serve

needs_2_devices = pytest.mark.skipif(
    len(jax.devices()) < 2, reason="needs >= 2 devices for TP/sharding"
)

# the Pallas decode kernel cannot GSPMD-partition, so TP forces the
# dense decode path; parity runs compare dense-vs-dense at BOTH widths
# (kernel-vs-dense equality is a different, unprobed claim)
CFG = TransformerConfig(
    vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
    max_len=32, decode_kernel=False,
)
_PARAMS = {}


def _params(seed=0):
    if seed not in _PARAMS:
        _PARAMS[seed] = init_transformer(jax.random.key(seed), CFG)
    return _PARAMS[seed]


def _engine(tp=1, n_slots=4, **kw):
    kw.setdefault("temperature", 0.0)
    kw.setdefault("decode_horizon", 2)
    return ServingEngine(
        CFG, _params(), n_slots=n_slots,
        retry_backoff_s=0.001, max_backoff_s=0.004, tp=tp, **kw,
    )


def _requests(n, seed=0, max_new=(4, 10)):
    rng = np.random.default_rng(seed)
    return [
        Request(
            prompt=rng.integers(0, 64, (int(rng.integers(3, 12)),))
            .astype(np.int32),
            max_new=int(rng.integers(*max_new)),
            id=f"r{seed}-{i}",
        )
        for i in range(n)
    ]


def _clone(reqs):
    return [
        Request(prompt=np.array(r.prompt), max_new=r.max_new, id=r.id)
        for r in reqs
    ]


def _run(engine, reqs):
    for r in reqs:
        engine.submit(r)
    engine.run()
    return {r.id: engine.pop_result(r.id) for r in reqs}


# -- tentpole (a): sharded decode byte parity ----------------------------


@needs_2_devices
@pytest.mark.parametrize("temperature", [0.0, 0.8],
                         ids=["greedy", "sampled"])
def test_tp2_streams_byte_identical_to_tp1(temperature):
    """The headline bar: TP=2 decode (sharded params, sharded KV pool,
    fused horizons, batched admission) produces byte-identical streams
    to the single-chip engine — greedy and sampled."""
    reqs = _requests(6, seed=1)
    base = _run(_engine(tp=1, temperature=temperature), reqs)

    reqs2 = _clone(reqs)
    eng = _engine(tp=2, temperature=temperature)
    assert eng.tp == 2, "construction-time parity probe fell back"
    assert eng.tp_mesh is not None
    got = _run(eng, reqs2)
    for r in reqs:
        assert np.array_equal(base[r.id], got[r.id]), r.id


@needs_2_devices
def test_tp_prefill_bucketing_parity_across_prompt_lengths():
    """Prompt lengths straddling several pow2 prefill buckets, so the
    sharded bucketed-prefill programs (not just decode) are compared."""
    rng = np.random.default_rng(7)
    reqs = [
        Request(prompt=rng.integers(0, 64, (ln,)).astype(np.int32),
                max_new=4, id=f"p{ln}")
        for ln in (1, 2, 3, 7, 8, 9, 15, 20)
    ]
    base = _run(_engine(tp=1, n_slots=8), reqs)
    reqs2 = _clone(reqs)
    eng = _engine(tp=2, n_slots=8)
    assert eng.tp == 2
    got = _run(eng, reqs2)
    for r in reqs:
        assert np.array_equal(base[r.id], got[r.id]), r.id


@needs_2_devices
def test_tp_crash_recovery_replay_parity():
    """Crash mid-horizon under TP=2: the supervised replay rebuilds the
    SHARDED caches and the recovered streams still match an unfaulted
    single-chip run byte-for-byte."""
    reqs = _requests(4, seed=3)
    clean = _run(_engine(tp=1), reqs)

    reqs2 = _clone(reqs)
    inj = FaultInjector().plan("step", at=1, kind="crash")
    eng = _engine(tp=2, faults=inj)
    assert eng.tp == 2
    got = _run(eng, reqs2)
    assert eng.metrics.n_restarts == 1
    for r in reqs:
        assert np.array_equal(clean[r.id], got[r.id]), r.id


def test_tp_requires_dividing_heads():
    """tp=3 cannot shard 4 heads: the engine must fall back to tp=1
    (conservative gating), not crash or mis-shard."""
    eng = _engine(tp=3)
    assert eng.tp == 1
    assert eng.tp_mesh is None


def test_tp1_is_the_unsharded_engine():
    eng = _engine(tp=1)
    assert eng.tp == 1 and eng.tp_mesh is None


# -- satellite: probe-verdict persistence --------------------------------


@needs_2_devices
def test_probe_cache_skips_reprobe_on_second_engine(tmp_path):
    """First engine pays the probe dispatches and persists verdicts;
    a second engine with the same (config, backend, geometry)
    constructs WITHOUT dispatching a single probe."""
    path = tmp_path / "probes.json"
    e1 = _engine(tp=2, probe_cache=str(path))
    assert e1.tp == 2
    assert "tp_parity" in e1.probes_run
    assert path.exists()
    # real traffic also runs (and persists) the lazy probes — batched
    # admission fires at the first multi-request admission wave
    reqs = _requests(4, seed=5)
    base = _run(e1, _clone(reqs))
    assert "batch_admission" in e1.probes_run

    e2 = _engine(tp=2, probe_cache=str(path))
    assert e2.tp == 2
    assert e2.probes_run == []
    assert "tp_parity" in e2.probes_from_cache

    # the same traffic through the cached-verdict engine: every
    # verdict comes from disk, zero probe dispatches end to end
    got = _run(e2, reqs)
    assert e2.probes_run == []
    assert "batch_admission" in e2.probes_from_cache
    for rid, toks in base.items():
        assert np.array_equal(toks, got[rid])


def test_probe_cache_key_separates_geometry(tmp_path):
    """Verdicts are keyed by config AND geometry: a different slot
    count or TP width must never reuse another geometry's verdict."""
    k1 = probe_key("tp_parity", CFG.to_json(), tp=2, max_total=32)
    k2 = probe_key("tp_parity", CFG.to_json(), tp=4, max_total=32)
    k3 = probe_key("tp_parity", CFG.to_json(), tp=2, max_total=64)
    assert len({k1, k2, k3}) == 3

    pc = ProbeCache(str(tmp_path / "p.json"))
    pc.put(k1, True)
    pc.put(k2, False)
    re = ProbeCache(str(tmp_path / "p.json"))
    assert re.get(k1) is True and re.get(k2) is False
    assert re.get(k3) is None


def test_probe_cache_tolerates_corrupt_file(tmp_path):
    path = tmp_path / "p.json"
    path.write_text("{not json")
    pc = ProbeCache(str(path))
    assert pc.get("anything") is None
    pc.put("k", True)
    assert ProbeCache(str(path)).get("k") is True


# -- satellite: hit-weighted prefix eviction -----------------------------


def test_hot_segment_outlives_colder_newer_ones():
    """Hit-count-weighted eviction: a pinned-then-unpinned segment that
    served many lookups survives region pressure that evicts colder
    segments inserted AFTER it (pure LRU would evict the hot one
    first)."""
    pool = KVSlotPool(CFG, 1, CFG.max_len)
    cache = PrefixCache(pool, 3 * pool.tpad)  # 3 region slots
    assert cache.hit_weight > 0

    hot = cache.insert(tuple(range(8)))[0]
    cache.unpin(hot)
    for _ in range(4):  # hot: refreshed by lookups
        seg, n = cache.lookup(tuple(range(8)) + (60, 61))
        assert seg is hot and n == 8
    # two colder segments, inserted later (higher last_use)
    c1 = cache.insert((50, 51, 52))[0]
    cache.unpin(c1)
    c2 = cache.insert((40, 41, 42))[0]
    cache.unpin(c2)

    # region full: the next insert must evict — and the victim must be
    # a cold segment despite the hot one having the OLDEST last_use
    cache.insert((30, 31, 32, 33))
    assert hot.alive, "hit-weighted eviction evicted the hot segment"
    assert not (c1.alive and c2.alive)
    assert cache.stats()["hits_recorded"] >= 4


def test_hit_weight_zero_restores_pure_lru():
    pool = KVSlotPool(CFG, 1, CFG.max_len)
    cache = PrefixCache(pool, 2 * pool.tpad, hit_weight=0.0)
    old = cache.insert(tuple(range(6)))[0]
    cache.unpin(old)
    for _ in range(10):
        cache.lookup(tuple(range(6)))
    newer = cache.insert((50, 51, 52))[0]
    cache.unpin(newer)
    cache.insert((40, 41, 42))
    assert not old.alive, "hit_weight=0 must fall back to pure LRU"
    assert newer.alive


# -- satellite: metrics scrape stays off-device --------------------------


def test_metrics_scrape_reads_host_metadata_only():
    """serve_kv_* and prefix_cache gauges must be scrape-safe: after a
    request has run, poison the live device arrays — a scrape that
    touched them (nbytes, shapes, stats) would raise / sync. Pins the
    zero-extra-dispatches-per-scrape contract."""
    eng = _engine(prefix_cache=True)
    _run(eng, _requests(2, seed=9))
    before = eng.metrics.render_prometheus()
    assert "serve_kv_cache_bytes" in before
    kv_bytes = eng.pool.nbytes()
    region_bytes = eng.prefix_cache.nbytes()
    assert kv_bytes > 0 and region_bytes > 0

    # poison: any device-array access during a scrape now explodes
    eng.pool.caches = None
    eng.prefix_cache.region = None

    text = eng.metrics.render_prometheus()
    line = next(
        ln for ln in text.splitlines()
        if ln.startswith("serve_kv_cache_bytes ")
    )
    assert float(line.split()[1]) == float(kv_bytes)
    line = next(
        ln for ln in text.splitlines()
        if ln.startswith("serve_prefix_region_bytes ")
    )
    assert float(line.split()[1]) == float(region_bytes)
    stats = eng.prefix_cache.stats()
    assert eng.pool.nbytes() == kv_bytes
    assert eng.prefix_cache.nbytes() == region_bytes
    assert stats["capacity_tokens"] == eng.prefix_cache.capacity_tokens


# -- tentpole (b): replica router ----------------------------------------


def _post(addr, body, timeout=60):
    conn = http.client.HTTPConnection(*addr, timeout=timeout)
    try:
        conn.request(
            "POST", "/v1/generate", body=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        r = conn.getresponse()
        return r.status, json.loads(r.read()), r.getheader("X-Served-By")
    finally:
        conn.close()


def _get(addr, path, timeout=10):
    conn = http.client.HTTPConnection(*addr, timeout=timeout)
    try:
        conn.request("GET", path)
        r = conn.getresponse()
        return r.status, r.read()
    finally:
        conn.close()


def _fleet(n=2, faults=None):
    """n in-process replicas (full ServingServer each) + a router."""
    servers = []
    for i in range(n):
        eng = ServingEngine(
            CFG, _params(), n_slots=4, temperature=0.0,
            decode_horizon=2, prefix_cache=True,
            retry_backoff_s=0.001, max_backoff_s=0.004,
            faults=(faults[i] if faults else None),
        )
        servers.append(ServingServer(eng, port=0).start())
    router = ReplicaRouter(
        [s.address for s in servers],
        affinity_min_match=6, health_interval_s=0.1,
    ).start()
    return router, servers


def test_prefix_shadow_trie():
    t = PrefixShadow()
    t.insert([1, 2, 3, 4])
    t.insert([1, 2, 9])
    assert t.longest_match([1, 2, 3, 4, 5]) == 4
    assert t.longest_match([1, 2, 9, 9]) == 3
    assert t.longest_match([7, 7]) == 0
    assert len(t) == 5  # 1-2-3-4 chain + the 9 branch node


def test_prefix_shadow_reset_at_cap():
    t = PrefixShadow(max_nodes=4)
    t.insert([1, 2, 3, 4])
    t.insert([5, 6])  # over cap: wholesale reset, then re-learn
    assert t.resets == 1
    assert t.longest_match([1, 2, 3, 4]) == 0
    assert t.longest_match([5, 6]) == 2


def test_router_least_loaded_spreads_and_affinity_pins():
    rng = np.random.default_rng(11)
    router, servers = _fleet(2)
    try:
        # distinct prompts spread over both replicas
        seen = set()
        for _ in range(4):
            p = rng.integers(0, 64, (8,)).tolist()
            st, out, served = _post(
                router.address, {"prompt": p, "max_new": 3})
            assert st == 200, out
            seen.add(served)
        assert len(seen) == 2, "least-loaded dispatch never spread"

        # shared-prefix prompts pin to ONE replica (affinity override)
        shared = rng.integers(0, 64, (10,)).tolist()
        pinned = set()
        for _ in range(5):
            p = shared + rng.integers(0, 64, (3,)).tolist()
            st, out, served = _post(
                router.address, {"prompt": p, "max_new": 3})
            assert st == 200, out
            pinned.add(served)
        assert len(pinned) == 1, f"affinity split the prefix: {pinned}"

        # the pinned replica's prefix cache actually got the reuse
        name = pinned.pop()
        hit_engines = [
            s.engine for s in servers
            if f"{s.address[0]}:{s.address[1]}" == name
        ]
        assert len(hit_engines) == 1
        m = hit_engines[0].metrics
        assert (m.n_prefix_hits_full + m.n_prefix_hits_partial) > 0

        st, raw = _get(router.address, "/metrics")
        assert st == 200 and b"router_affinity_total" in raw
    finally:
        router.stop()
        for s in servers:
            s.stop()


def test_router_serves_through_single_replica_crash_recovery():
    """Per-replica chaos: replica 1's engine crashes mid-decode and its
    own supervisor replays it; the router keeps serving BOTH replicas'
    traffic with zero failed requests (the crashed replica's in-flight
    set recovers via replay, byte-identical by the chaos suite's
    bar)."""
    rng = np.random.default_rng(13)
    faults = [None, FaultInjector().plan("step", at=2, kind="crash")]
    router, servers = _fleet(2, faults=faults)
    try:
        results = []
        for _ in range(8):
            p = rng.integers(0, 64, (7,)).tolist()
            st, out, served = _post(
                router.address, {"prompt": p, "max_new": 5})
            results.append((st, served))
        assert all(st == 200 for st, _ in results), results
        assert {s for _, s in results} == {
            f"{s.address[0]}:{s.address[1]}" for s in servers
        }, "both replicas must have served through the crash"
        crashed = servers[1].engine.metrics.n_restarts
        assert crashed == 1, "the planned crash never exercised replay"
    finally:
        router.stop()
        for s in servers:
            s.stop()


def test_router_retries_onto_survivor_when_replica_dies():
    """Hard replica death: the router marks it unhealthy on the first
    failed forward and every subsequent request lands on the survivor;
    /healthz stays 200 (degraded, not down)."""
    rng = np.random.default_rng(17)
    router, servers = _fleet(2)
    try:
        for _ in range(2):  # prime both shadows
            p = rng.integers(0, 64, (6,)).tolist()
            assert _post(router.address,
                         {"prompt": p, "max_new": 3})[0] == 200
        servers[0].stop()
        survivor = f"{servers[1].address[0]}:{servers[1].address[1]}"
        for _ in range(4):
            p = rng.integers(0, 64, (6,)).tolist()
            st, out, served = _post(
                router.address, {"prompt": p, "max_new": 3})
            assert st == 200, out
            assert served == survivor
        router.poll_health()
        st, raw = _get(router.address, "/healthz")
        assert st == 200
        payload = json.loads(raw)
        assert payload["ok"] and payload["healthy"] == [survivor]
        st, raw = _get(router.address, "/replicas")
        assert st == 200
        states = json.loads(raw)
        assert states[survivor]["healthy"]
    finally:
        router.stop()
        for s in servers[1:]:
            s.stop()


def test_router_503_when_no_replica_left():
    router, servers = _fleet(1)
    try:
        servers[0].stop()
        router.poll_health()
        st, out, served = _post(
            router.address, {"prompt": [1, 2, 3], "max_new": 2})
        assert st == 503 and served is None
        st, _ = _get(router.address, "/healthz")
        assert st == 503
    finally:
        router.stop()


def test_router_rejects_malformed_and_unknown():
    router, servers = _fleet(1)
    try:
        conn = http.client.HTTPConnection(*router.address, timeout=10)
        conn.request("POST", "/v1/generate", body=b"{oops",
                     headers={"Content-Type": "application/json"})
        assert conn.getresponse().status == 400
        conn.close()
        st, _ = _get(router.address, "/nope")
        assert st == 404
    finally:
        router.stop()
        for s in servers:
            s.stop()


@needs_2_devices
def test_router_over_tp_replicas():
    """The full PR-6 stack: two replicas EACH serving with TP=2 behind
    the affinity router; streams match the single-chip engine
    byte-for-byte through the whole fleet path."""
    reqs = _requests(4, seed=19, max_new=(3, 6))
    base = _run(_engine(tp=1), _clone(reqs))

    servers = []
    for _ in range(2):
        eng = ServingEngine(
            CFG, _params(), n_slots=4, temperature=0.0,
            decode_horizon=2, tp=2,
            retry_backoff_s=0.001, max_backoff_s=0.004,
        )
        assert eng.tp == 2
        servers.append(ServingServer(eng, port=0).start())
    router = ReplicaRouter(
        [s.address for s in servers], affinity_min_match=6,
    ).start()
    try:
        for r in reqs:
            st, out, _ = _post(router.address, {
                "prompt": [int(t) for t in r.prompt],
                "max_new": r.max_new,
            })
            assert st == 200, out
            assert out["tokens"] == [int(t) for t in base[r.id]], r.id
    finally:
        router.stop()
        for s in servers:
            s.stop()
