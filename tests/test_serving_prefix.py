"""Prefix-cache suite: radix-tree KV reuse across serving requests.

The load-bearing property mirrors ``test_serving.py``'s: byte-identical
token streams — now with the prefix cache ON vs OFF, greedy AND
sampled, including crash-recovery replay mid-generation on a cache-hit
request. That holds because hit-path reuse is gated by a one-time
bitwise parity probe (copy-cached-rows + chunk-computed suffix must
reproduce the full bucketed prefill exactly, KV rows and logits), and
a FULL hit replays the exact ``(1, V)`` logits captured at insert time
— so the cache can only ever change WHERE bytes come from, never which
bytes. The second contract is the refcount boundary: eviction never
drops a segment a live admission read (pinned until retirement), no
matter the region pressure.
"""

import numpy as np
import pytest

import jax

from deeplearning4j_tpu.models.transformer import (
    TransformerConfig,
    init_transformer,
    transformer_generate,
)
from deeplearning4j_tpu.serving import (
    FaultInjector,
    KVSlotPool,
    PrefixCache,
    Request,
    RequestScheduler,
    ServingEngine,
)

pytestmark = pytest.mark.prefix

CFG = TransformerConfig(
    vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64, max_len=32
)
_PARAMS = {}


def _params(seed=0):
    if seed not in _PARAMS:
        _PARAMS[seed] = init_transformer(jax.random.key(seed), CFG)
    return _PARAMS[seed]


def _engine(n_slots=2, **kw):
    kw.setdefault("temperature", 0.0)
    return ServingEngine(
        CFG, _params(), n_slots=n_slots,
        retry_backoff_s=0.001, max_backoff_s=0.004, **kw,
    )


def _shared_prefix_requests():
    """Requests dominated by two shared prefixes (system-prompt
    traffic) plus unrelated fillers, prompts varied enough that the
    radix tree sees splits, extensions, and misses."""
    a = np.arange(1, 9, dtype=np.int32)          # 8 = bucket grain
    b = np.arange(40, 56, dtype=np.int32)        # 16 tokens
    prompts = [
        a,                                        # seeds segment A
        np.concatenate([a, [60, 61]]),            # partial hit on A
        b,                                        # seeds segment B
        a.copy(),                                 # full hit on A
        np.concatenate([b, [3, 4, 5]]),           # partial hit on B
        np.arange(20, 27, dtype=np.int32),        # miss (7 tokens)
        np.concatenate([a, [62]]),                # partial hit on A
        b.copy(),                                 # full hit on B
    ]
    return [Request(prompt=p.copy(), max_new=5 + (i % 3))
            for i, p in enumerate(prompts)]


def _drive(engine, reqs):
    for r in reqs:
        engine.submit(r)
    engine.run()
    return [engine.results[r.id] for r in reqs]


def _assert_streams_equal(sa, sb):
    for x, y in zip(sa, sb):
        np.testing.assert_array_equal(x, y)


# -- byte parity ---------------------------------------------------------


@pytest.mark.parametrize("temperature", [0.0, 0.8])
def test_prefix_cache_on_off_byte_parity(temperature):
    """Cache on vs off: byte-identical streams under slot contention
    (n_slots=2 over 8 requests forces multi-round admission, so later
    rounds actually hit segments earlier rounds inserted) — and the
    cache must have REALLY been exercised: full and partial hits > 0,
    saved prefill tokens > 0."""
    off = _drive(_engine(temperature=temperature, prefix_cache=False),
                 _shared_prefix_requests())
    # Region sized to the working set (8 slots): the default (one slot
    # per decode slot = 2 here) LRU-churns under 7 inserts, which is
    # legal but leaves nothing for the repeats to hit.
    eng = _engine(temperature=temperature, prefix_cache=True,
                  prefix_cache_tokens=8 * CFG.max_len)
    on = _drive(eng, _shared_prefix_requests())
    _assert_streams_equal(off, on)
    m = eng.metrics
    assert m.n_prefix_hits_full > 0
    assert m.n_prefix_hits_partial > 0
    assert m.prefix_tokens_saved > 0
    s = m.summary()
    assert s["prefix_hit_rate"] > 0
    assert s["prefix_tokens_saved"] == m.prefix_tokens_saved


def test_greedy_matches_per_request_generate():
    """Cache-on streams equal each request decoded alone through the
    plain generate path — the same reference contract the serving
    suite pins, now through hit-path admissions."""
    gen = jax.jit(
        transformer_generate(CFG),
        static_argnames=("max_new", "temperature", "top_k"),
    )
    reqs = _shared_prefix_requests()
    streams = _drive(_engine(prefix_cache=True), reqs)
    for r, got in zip(reqs, streams):
        ref = np.asarray(gen(
            _params(), np.asarray(r.prompt[None]), jax.random.key(0),
            max_new=r.max_new, temperature=0.0,
        ))[0]
        np.testing.assert_array_equal(got, ref)


# -- hit mechanics -------------------------------------------------------


def test_full_hit_dispatches_zero_prefill_programs():
    """A fully-cached admission is ONE pure-copy program: segment slab
    + stored logits. The prefill-dispatch counter (programs that
    compute prompt rows) must not move at all."""
    eng = _engine(n_slots=1, prefix_cache=True)
    p = np.arange(1, 9, dtype=np.int32)
    r1 = Request(prompt=p.copy(), max_new=6)
    eng.submit(r1)
    eng.run()
    assert eng.prefill_dispatches > 0  # the miss admission computed
    before = eng.prefill_dispatches
    r2 = Request(prompt=p.copy(), max_new=6)
    eng.submit(r2)
    eng.run()
    assert eng.prefill_dispatches == before
    assert eng.metrics.n_prefix_hits_full == 1
    np.testing.assert_array_equal(eng.results[r1.id], eng.results[r2.id])


def test_partial_hit_reuses_prefix_and_saves_tokens():
    """A prompt extending a cached one chunk-computes only the suffix:
    matched tokens counted as saved, one suffix dispatch, stream still
    byte-equal to the uncached engine."""
    a = np.arange(1, 17, dtype=np.int32)               # 16 tokens
    b = np.concatenate([a, [60, 61, 62, 63]])          # extends a
    def run(cache):
        eng = _engine(n_slots=1, prefix_cache=cache)
        ra = Request(prompt=a.copy(), max_new=4)
        rb = Request(prompt=b.copy(), max_new=4)
        out = _drive(eng, [ra, rb])
        return eng, out
    e_off, off = run(False)
    e_on, on = run(True)
    _assert_streams_equal(off, on)
    assert e_on.metrics.n_prefix_hits_partial == 1
    assert e_on.metrics.prefix_tokens_saved == 16
    # the hit admission dispatched exactly one program (the suffix
    # window) — same count as the miss here, but over 8 rows not 32
    assert e_on.prefill_dispatches == e_off.prefill_dispatches


def test_branch_point_segment_enables_shared_prefix_hits():
    """System-prompt traffic: prompts share a 16-token prefix but all
    END differently, so no full prompt is a prefix of another and leaf
    segments alone can never match. The segment minted at the radix
    BRANCH POINT (when the second insert splits the first's edge) is
    what makes the third request hit — and, carrying no stored logits,
    it must serve partial hits only, byte-identically."""
    shared = np.arange(1, 17, dtype=np.int32)
    prompts = [np.concatenate([shared, [50 + i, 60 + i]]).astype(np.int32)
               for i in range(4)]
    def run(cache):
        eng = _engine(n_slots=1, prefix_cache=cache,
                      prefix_cache_tokens=8 * CFG.max_len)
        return eng, _drive(eng, [Request(prompt=p.copy(), max_new=3)
                                 for p in prompts])
    e_off, off = run(False)
    e_on, on = run(True)
    _assert_streams_equal(off, on)
    m = e_on.metrics
    # req 0 misses; req 1 misses but its insert mints the branch
    # segment at the shared prefix; reqs 2 and 3 partial-hit it
    assert m.n_prefix_hits_partial == 2
    assert m.n_prefix_hits_full == 0
    assert m.prefix_tokens_saved == 32
    # an exact-length query against the logits-less branch segment
    # must degrade to a partial hit, never a bogus full hit
    r = Request(prompt=shared.copy(), max_new=3)
    e_on.submit(r)
    e_on.run()
    assert m.n_prefix_hits_full == 0 and m.n_prefix_hits_partial == 3


def test_metrics_appear_in_prometheus_render():
    eng = _engine(n_slots=1, prefix_cache=True, adaptive_horizon=True)
    p = np.arange(1, 9, dtype=np.int32)
    _drive(eng, [Request(prompt=p.copy(), max_new=4),
                 Request(prompt=p.copy(), max_new=4)])
    text = eng.metrics.render_prometheus()
    assert 'serve_prefix_lookups_total{result="hit_full"} 1' in text
    assert 'serve_prefix_lookups_total{result="miss"} 1' in text
    assert "serve_prefix_tokens_saved_total 8" in text
    assert "serve_prefix_inserts_total 1" in text
    assert "serve_prefix_segments 1" in text
    assert "serve_prefix_capacity_tokens" in text
    assert "serve_decode_horizon_current" in text


# -- crash recovery ------------------------------------------------------


@pytest.mark.chaos
def test_recovery_mid_generation_on_cache_hit_request():
    """Engine crash while a cache-hit request is mid-generation
    (sampled): replay recovery reinits the region (corrupt after a
    crash) and replays through the same lookup path — every lookup
    misses against the empty tree, i.e. the cold branch — so the
    recovered streams stay byte-identical to an unfaulted cache-on
    run AND to the cache-off engine."""
    p = np.arange(1, 9, dtype=np.int32)
    def drive(eng):
        reqs = [Request(prompt=p.copy(), max_new=8) for _ in range(2)]
        return _drive(eng, reqs), eng
    r_off, _ = drive(_engine(n_slots=1, temperature=0.7))
    r_on, e_on = drive(_engine(n_slots=1, temperature=0.7,
                               prefix_cache=True))
    assert e_on.metrics.n_prefix_hits_full == 1  # hit request exists
    # crash strikes after the second (full-hit) admission dispatched
    inj = FaultInjector().plan("step", at=10, kind="crash")
    r_cr, e_cr = drive(_engine(n_slots=1, temperature=0.7,
                               prefix_cache=True, faults=inj))
    assert e_cr.metrics.n_restarts == 1
    assert e_cr.metrics.n_prefix_hits_full == 1
    _assert_streams_equal(r_off, r_on)
    _assert_streams_equal(r_on, r_cr)
    # the rebuilt cache is coherent: the first post-recovery admission
    # misses (reinit dropped every segment) and re-seeds the tree, the
    # next one full-hits with zero prefill dispatches again
    x1 = Request(prompt=p.copy(), max_new=4)
    e_cr.submit(x1)
    e_cr.run()
    before = e_cr.prefill_dispatches
    x2 = Request(prompt=p.copy(), max_new=4)
    e_cr.submit(x2)
    e_cr.run()
    assert e_cr.prefill_dispatches == before  # full hit, pure copy
    assert e_cr.metrics.n_prefix_hits_full == 2


# -- eviction / refcounts ------------------------------------------------


@pytest.mark.chaos
def test_eviction_never_drops_pinned_segment():
    """Region sized to ONE segment, two concurrent admissions: the
    second insert must DECLINE (the only slot is pinned by the live
    first request), never evict it. After retirement unpins, the next
    insert evicts normally."""
    eng = _engine(n_slots=2, prefix_cache=True,
                  prefix_cache_tokens=1)  # rounds up to 1 region slot
    cache = eng.prefix_cache
    assert cache.n_region_slots == 1
    a = np.arange(1, 9, dtype=np.int32)
    b = np.arange(30, 40, dtype=np.int32)
    ra = Request(prompt=a.copy(), max_new=6)
    rb = Request(prompt=b.copy(), max_new=6)
    eng.submit(ra)
    eng.submit(rb)
    eng.step()  # admits both; first insert claims the slot, pinned
    assert cache.n_segments == 1
    assert cache.n_pinned == 1
    (seg,) = cache._segments
    assert seg.alive and seg.refs > 0
    assert cache.n_insert_declined >= 1  # second insert backed off
    eng.run()
    assert cache.n_pinned == 0  # retirement unpinned
    # now an insert may evict: a third, different prompt takes the slot
    rc = Request(prompt=np.arange(50, 60, dtype=np.int32), max_new=4)
    eng.submit(rc)
    eng.run()
    assert cache.n_evictions == 1
    assert not seg.alive
    assert eng.metrics.n_prefix_evictions == 1


def test_lru_eviction_prefers_least_recently_used():
    pool = KVSlotPool(CFG, 1, CFG.max_len)
    cache = PrefixCache(pool, 2 * pool.tpad)
    assert cache.n_region_slots == 2
    (s1,) = cache.insert(range(1, 9))
    (s2,) = cache.insert(range(11, 19))
    cache.unpin(s1)
    cache.unpin(s2)
    cache.lookup(range(1, 9))  # refresh s1's LRU tick
    (s3,) = cache.insert(range(21, 29))
    assert s3 is not None
    assert not s2.alive and s1.alive  # s2 was least recent
    assert cache.n_evictions == 1
    # all pinned -> insert declines instead of evicting
    cache.unpin(s3)
    cache.pin(s1)
    cache.pin(s3)
    assert cache.insert(range(31, 39)) == []
    assert cache.n_insert_declined == 1


# -- radix tree ----------------------------------------------------------


def test_radix_tree_split_lookup_prune():
    pool = KVSlotPool(CFG, 1, CFG.max_len)
    cache = PrefixCache(pool, 4 * pool.tpad)
    (long,) = cache.insert([1, 2, 3, 4, 5, 6])
    cache.unpin(long)
    # inserting a strict prefix splits the edge; both remain cached
    (short,) = cache.insert([1, 2, 3])
    cache.unpin(short)
    assert cache.n_segments == 2
    # deepest live segment wins; matched_len == segment.length
    seg, m = cache.lookup([1, 2, 3, 4, 5, 6, 7, 8])
    assert seg is long and m == 6
    seg, m = cache.lookup([1, 2, 3, 4])
    assert seg is short and m == 3
    seg, m = cache.lookup([1, 2])
    assert seg is None and m == 0  # segments only at node boundaries
    assert cache.lookup([9, 9])[0] is None
    # duplicate insert declines quietly (already cached)
    assert cache.insert([1, 2, 3]) == []
    # evicting the deep segment falls back to the shorter prefix
    cache.pin(short)
    (s3,) = cache.insert([7, 7, 7])
    (s4,) = cache.insert([8, 8, 8])
    (s5,) = cache.insert([9, 9, 9])  # evicts `long` (only unpinned)
    assert s3 and s4 and s5 and not long.alive
    seg, m = cache.lookup([1, 2, 3, 4, 5, 6])
    assert seg is short and m == 3
    # reinit drops everything (crash recovery)
    cache.reinit()
    assert cache.n_segments == 0 and cache.n_pinned == 0
    assert cache.lookup([1, 2, 3])[0] is None


# -- batched admission ---------------------------------------------------


def test_batched_admission_parity_and_fewer_dispatches():
    """Four same-bucket misses admitted in one horizon: batched
    admission coalesces them into ONE dispatched prefill program,
    byte-identical to serial admission."""
    rng = np.random.default_rng(3)
    prompts = [rng.integers(0, CFG.vocab_size, (6 + i % 3,)).astype(np.int32)
               for i in range(4)]
    def run(batch):
        eng = _engine(n_slots=4, batch_admission=batch)
        reqs = [Request(prompt=p.copy(), max_new=5) for p in prompts]
        return eng, _drive(eng, reqs)
    e_ser, ser = run(False)
    e_bat, bat = run("auto")
    _assert_streams_equal(ser, bat)
    assert e_bat.metrics.n_batched_admissions == 4
    assert e_ser.metrics.n_batched_admissions == 0
    assert e_bat.prefill_dispatches == 1   # one group program
    assert e_ser.prefill_dispatches == 4   # one per request


def test_batched_partial_hits_share_one_dispatch():
    """Several prompts extending the SAME cached prefix, admitted in
    one horizon: the batched hit program computes every suffix in one
    dispatch (the many-requests-behind-one-system-prompt case)."""
    a = np.arange(1, 9, dtype=np.int32)
    exts = [np.concatenate([a, [50 + i, 60 - i]]) for i in range(3)]
    def run(cache):
        eng = _engine(n_slots=3, prefix_cache=cache)
        seed = Request(prompt=a.copy(), max_new=4)
        _drive(eng, [seed])
        before = eng.prefill_dispatches
        reqs = [Request(prompt=p.copy(), max_new=4) for p in exts]
        return eng, _drive(eng, reqs), eng.prefill_dispatches - before
    e_off, off, _ = run(False)
    e_on, on, delta = run(True)
    _assert_streams_equal(off, on)
    assert e_on.metrics.n_prefix_hits_partial == 3
    assert e_on.metrics.prefix_tokens_saved == 24
    assert delta == 1  # one batched suffix program for all three
    assert e_on.metrics.n_batched_admissions == 3


# -- adaptive horizon ----------------------------------------------------


def test_adaptive_horizon_shrinks_then_restores():
    """With requests queued, the dispatched horizon drops to 1 (the
    next admission boundary is one substep away); once the queue
    drains the configured K is restored. Streams are unchanged —
    the device stopping rule is per-substep."""
    p = np.arange(1, 9, dtype=np.int32)
    def reqs():
        return [Request(prompt=p.copy(), max_new=6) for _ in range(2)]
    fixed = _drive(_engine(n_slots=1, decode_horizon=4), reqs())
    eng = _engine(n_slots=1, decode_horizon=4, adaptive_horizon=True)
    rs = reqs()
    for r in rs:
        eng.submit(r)
    seen = set()
    while not eng.idle:
        eng.step()
        seen.add(eng.decode_horizon_current)
    adaptive = [eng.results[r.id] for r in rs]
    _assert_streams_equal(fixed, adaptive)
    assert seen == {1, 4}  # shrank while queued, restored after drain
    assert eng.decode_horizon_current == 4
    assert "serve_decode_horizon_current" in eng.metrics.render_prometheus()


# -- scheduler prefix affinity -------------------------------------------


def test_scheduler_prefix_affinity_promotes_matches():
    sched = RequestScheduler(prefix_affinity_tokens=4)
    pre = np.arange(1, 9, dtype=np.int32)
    r1 = Request(prompt=pre.copy(), max_new=2)
    r2 = Request(prompt=np.arange(40, 48, dtype=np.int32), max_new=2)
    r3 = Request(prompt=np.concatenate([pre, [9]]), max_new=2)
    for r in (r1, r2, r3):
        sched.submit(r)
    assert sched.pop() is r1
    assert sched.pop(affinity_hint=r1.prompt) is r3  # promoted over r2
    assert sched.pop(affinity_hint=r3.prompt) is r2  # plain FIFO now
    # affinity never crosses a priority boundary
    hi = Request(prompt=np.arange(50, 58, dtype=np.int32), max_new=2,
                 priority=0)
    lo = Request(prompt=pre.copy(), max_new=2, priority=1)
    sched.submit(lo)
    sched.submit(hi)
    assert sched.pop(affinity_hint=pre) is hi


# -- slot pool determinism (satellite) -----------------------------------


def test_slot_pool_free_list_lowest_index_first():
    pool = KVSlotPool(CFG, 4, CFG.max_len)
    assert [pool.acquire() for _ in range(4)] == [0, 1, 2, 3]
    pool.release(2)
    pool.release(0)
    assert pool.acquire() == 0  # lowest free index, not LIFO
    assert pool.acquire() == 2
    with pytest.raises(RuntimeError):
        pool.acquire()
    with pytest.raises(ValueError):
        pool.release(7)


def test_slot_pool_generation_counter_detects_reuse():
    """The generation counter is what lets pipelined readback discard
    a token block that raced a slot's retire/re-acquire."""
    pool = KVSlotPool(CFG, 2, CFG.max_len)
    s = pool.acquire()
    g1 = pool.generation(s)
    pool.release(s)
    assert pool.acquire() == s  # deterministically the same slot
    g2 = pool.generation(s)
    assert g2 == g1 + 1  # a stale block's gen no longer matches
    other = pool.acquire()
    assert pool.generation(other) == 1


# -- paged pool block determinism (satellite) ----------------------------


def _paged_pool(n_slots=2, block_size=8):
    from deeplearning4j_tpu.serving import PagedKVPool
    return PagedKVPool(CFG, n_slots, CFG.max_len, block_size=block_size)


def test_paged_pool_block_alloc_lowest_id_first():
    """Block ids come off a heap lowest-first (the block analogue of
    the slot free-list test): allocation order is a pure function of
    the request sequence, so identical runs produce identical tables."""
    pool = _paged_pool()
    s = pool.acquire()
    pool.alloc_slot_blocks(s, 17)  # ceil(17/8) = 3 blocks
    assert pool.slot_blocks(s) == [1, 2, 3]  # 0 is the zero sentinel
    pool.release(s)
    assert pool.n_blocks_in_use == 0
    s2 = pool.acquire()
    pool.alloc_slot_blocks(s2, 9)
    assert pool.slot_blocks(s2) == [1, 2]  # freed ids reused, lowest first
    extra = pool.alloc_blocks(2)
    assert extra == [3, 4]
    with pytest.raises(RuntimeError):
        pool.alloc_blocks(pool.n_free_blocks + 1)


def test_paged_pool_generation_counter_spans_block_reuse():
    """Slot reuse bumps the generation even though the slot's KV now
    lives in reallocated blocks — a stale pipelined readback keyed on
    (slot, gen) is still discarded after the block-table rewrite."""
    pool = _paged_pool(n_slots=1)
    s = pool.acquire()
    pool.alloc_slot_blocks(s, 16)
    g1 = pool.generation(s)
    old_blocks = pool.slot_blocks(s)
    pool.release(s)
    assert pool.table(s).tolist() == [0] * pool.blocks_per_slot
    s2 = pool.acquire()
    assert s2 == s
    pool.alloc_slot_blocks(s2, 16)
    assert pool.generation(s2) == g1 + 1
    assert pool.slot_blocks(s2) == old_blocks  # same bytes, new gen


def test_paged_pool_snapshot_identity_at_block_granularity():
    """Two pools driven through the same acquire/alloc/alias/release
    sequence end with byte-identical block tables and refcounts — the
    block-granular snapshot-identity contract recovery replay and the
    prefix cache's aliasing both lean on."""
    def drive(pool):
        a = pool.acquire()
        b = pool.acquire()
        pool.alloc_slot_blocks(a, 20)
        pool.alloc_slot_blocks(b, 8)
        shared = pool.slot_blocks(a)[:2]
        pool.release(b)
        b2 = pool.acquire()
        pool.alias_into_slot(b2, shared)
        pool.alloc_slot_blocks(b2, 24, start=2)
        return pool

    p1 = drive(_paged_pool())
    p2 = drive(_paged_pool())
    np.testing.assert_array_equal(p1.tables(), p2.tables())
    assert [p1.refcount(i) for i in range(p1.n_blocks)] == \
           [p2.refcount(i) for i in range(p2.n_blocks)]
    # the aliased blocks really are shared (refcount 2), and releasing
    # one owner keeps them alive for the other
    shared = p1.slot_blocks(0)[:2]
    assert all(p1.refcount(b) == 2 for b in shared)
    p1.release(0)
    assert all(p1.refcount(b) == 1 for b in shared)
    assert p1.slot_blocks(1)[:2] == shared


def test_paged_pool_reinit_restores_full_capacity():
    """reinit() after a crash returns every block to the free heap and
    zeroes every table — the pool-side half of the recovery contract
    (PrefixCache.reinit drops its segment block refs WITHOUT decref,
    relying on exactly this)."""
    pool = _paged_pool()
    a = pool.acquire()
    pool.alloc_slot_blocks(a, 32)
    assert pool.n_blocks_in_use > 0
    pool.reinit()
    assert pool.n_blocks_in_use == 0
    assert pool.n_free_blocks == pool.n_blocks - 1  # all but sentinel
    assert pool.tables().sum() == 0
    assert pool.refcount(0) == 1  # sentinel stays pinned
