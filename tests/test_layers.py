"""Layer math tests ≙ reference RBMTests / AutoEncoderTest /
ConvolutionDownSampleLayerTest / LSTMTest, plus gradient checks the
reference never had (SURVEY §4 gap)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import rng
from deeplearning4j_tpu.nn import conf as C
from deeplearning4j_tpu.nn import layers


def _sgd(params, grads, lr):
    return jax.tree.map(lambda p, g: p - lr * g, params, grads)


def test_dense_forward_shapes():
    mod = layers.get("dense")
    cfg = C.LayerConfig(layer_type="dense", n_in=10, n_out=5, activation="tanh")
    p = mod.init(jax.random.key(0), cfg)
    assert p["W"].shape == (10, 5) and p["b"].shape == (5,)
    x = jnp.ones((4, 10))
    out = mod.activate(p, cfg, x)
    assert out.shape == (4, 5)
    assert jnp.allclose(out, jnp.tanh(x @ p["W"] + p["b"]))


def test_dense_dropout_only_in_training():
    mod = layers.get("dense")
    cfg = C.LayerConfig(n_in=8, n_out=4, dropout=0.5)
    p = mod.init(jax.random.key(0), cfg)
    x = jnp.ones((2, 8))
    eval_out = mod.activate(p, cfg, x, key=jax.random.key(1), training=False)
    assert jnp.allclose(eval_out, mod.activate(p, cfg, x))
    train_out = mod.activate(p, cfg, x, key=jax.random.key(1), training=True)
    assert not jnp.allclose(eval_out, train_out)


def test_output_layer_gradient_improves_score():
    mod = layers.get("output")
    cfg = C.LayerConfig(
        layer_type="output", n_in=4, n_out=3, activation="softmax", loss="MCXENT"
    )
    p = mod.init(jax.random.key(0), cfg)
    k = jax.random.key(42)
    x = jax.random.normal(k, (32, 4))
    y = jax.nn.one_hot(jax.random.randint(jax.random.key(1), (32,), 0, 3), 3)
    s0, g = mod.supervised_gradient(p, cfg, x, y)
    for _ in range(50):
        _, g = mod.supervised_gradient(p, cfg, x, y)
        p = _sgd(p, g, 0.5)
    s1 = mod.supervised_score(p, cfg, x, y)
    assert s1 < s0


@pytest.mark.parametrize(
    "visible,hidden",
    [
        (C.VisibleUnit.BINARY, C.HiddenUnit.BINARY),
        (C.VisibleUnit.GAUSSIAN, C.HiddenUnit.RECTIFIED),
        (C.VisibleUnit.BINARY, C.HiddenUnit.SOFTMAX),
        (C.VisibleUnit.SOFTMAX, C.HiddenUnit.BINARY),
        (C.VisibleUnit.LINEAR, C.HiddenUnit.GAUSSIAN),
    ],
)
def test_rbm_unit_type_shapes(visible, hidden):
    mod = layers.get("rbm")
    cfg = C.LayerConfig(
        layer_type="rbm", n_in=6, n_out=4, visible_unit=visible, hidden_unit=hidden, k=2
    )
    p = mod.init(jax.random.key(0), cfg)
    x = jax.random.uniform(jax.random.key(1), (8, 6))
    score, grads = mod.gradient(p, cfg, x, jax.random.key(2))
    assert jnp.isfinite(score)
    assert grads["W"].shape == (6, 4)
    assert grads["b"].shape == (4,)
    assert grads["vb"].shape == (6,)
    h = mod.activate(p, cfg, x)
    assert h.shape == (8, 4)


@pytest.mark.slow
def test_rbm_cdk_learns_mnist_like_patterns():
    """CD-1 should reduce reconstruction error on structured binary data
    (≙ RBMTests' toy-matrix convergence checks)."""
    mod = layers.get("rbm")
    cfg = C.LayerConfig(layer_type="rbm", n_in=12, n_out=8, k=1, lr=0.1)
    p = mod.init(jax.random.key(0), cfg)
    # two prototype patterns + noise
    protos = jnp.array([[1, 1, 1, 1, 0, 0, 0, 0, 1, 1, 0, 0],
                        [0, 0, 0, 0, 1, 1, 1, 1, 0, 0, 1, 1]], dtype=jnp.float32)
    ks = rng.KeyStream(3)
    x = protos[jax.random.randint(ks.next(), (64,), 0, 2)]
    flip = jax.random.bernoulli(ks.next(), 0.05, x.shape)
    x = jnp.abs(x - flip.astype(x.dtype))

    s0 = float(mod.score(p, cfg, x, ks.next()))
    step = jax.jit(
        lambda p, k: _sgd(p, mod.gradient(p, cfg, x, k)[1], 0.1)
    )
    for _ in range(100):
        p = step(p, ks.next())
    s1 = float(mod.score(p, cfg, x, ks.next()))
    assert s1 < s0 * 0.8, (s0, s1)


def test_rbm_free_energy_prefers_training_patterns():
    """After CD training the model assigns lower free energy (higher prob)
    to training patterns than to unrelated noise.  (Absolute free energy is
    only defined up to the partition function, so this relative check is
    the meaningful one.)"""
    mod = layers.get("rbm")
    cfg = C.LayerConfig(layer_type="rbm", n_in=12, n_out=8, k=1)
    p = mod.init(jax.random.key(0), cfg)
    protos = jnp.array(
        [[1, 1, 1, 1, 0, 0, 0, 0, 1, 1, 0, 0], [0, 0, 0, 0, 1, 1, 1, 1, 0, 0, 1, 1]],
        dtype=jnp.float32,
    )
    x = protos[jax.random.randint(jax.random.key(1), (64,), 0, 2)]
    ks = rng.KeyStream(2)
    step = jax.jit(lambda p, k: _sgd(p, mod.gradient(p, cfg, x, k)[1], 0.1))
    for _ in range(150):
        p = step(p, ks.next())
    noise = (jax.random.uniform(ks.next(), (64, 12)) > 0.5).astype(jnp.float32)
    fe_data = float(mod.free_energy(p, cfg, x)) / 64
    fe_noise = float(mod.free_energy(p, cfg, noise)) / 64
    assert fe_data < fe_noise, (fe_data, fe_noise)


@pytest.mark.slow
def test_autoencoder_denoising_learns():
    mod = layers.get("autoencoder")
    cfg = C.LayerConfig(
        layer_type="autoencoder", n_in=10, n_out=6, corruption_level=0.3
    )
    p = mod.init(jax.random.key(0), cfg)
    x = (jax.random.uniform(jax.random.key(1), (32, 10)) > 0.5).astype(jnp.float32)
    ks = rng.KeyStream(2)
    s0 = float(mod.score(p, cfg, x, ks.next()))
    step = jax.jit(lambda p, k: _sgd(p, mod.gradient(p, cfg, x, k)[1], 0.5))
    for _ in range(200):
        p = step(p, ks.next())
    s1 = float(mod.score(p, cfg, x, ks.next()))
    assert s1 < s0
    h = mod.encode(p, cfg, x)
    assert h.shape == (32, 6)
    recon = mod.reconstruct(p, cfg, x)
    assert recon.shape == x.shape


def test_conv_downsample_shapes_and_backward():
    """Forward shape parity with ConvolutionDownSampleLayerTest, plus the
    backward pass the reference never implemented (getGradient==null)."""
    mod = layers.get("conv_downsample")
    cfg = C.LayerConfig(
        layer_type="conv_downsample",
        n_in=1,
        num_feature_maps=4,
        filter_size=(5, 5),
        stride=(2, 2),
        activation="relu",
    )
    p = mod.init(jax.random.key(0), cfg)
    x = jax.random.normal(jax.random.key(1), (2, 28, 28, 1))
    out = mod.activate(p, cfg, x)
    assert out.shape == mod.output_shape(cfg, x.shape) == (2, 12, 12, 4)

    # real backward: d(sum(activate))/dW exists and is finite
    g = jax.grad(lambda p: mod.activate(p, cfg, x).sum())(p)
    assert jnp.all(jnp.isfinite(g["convweights"]))
    assert float(jnp.abs(g["convweights"]).max()) > 0


def test_lstm_forward_and_bptt():
    mod = layers.get("lstm")
    v = 8  # vocab == input == hidden (char-RNN convention)
    cfg = C.LayerConfig(layer_type="lstm", n_in=v, n_out=v, activation="tanh")
    p = mod.init(jax.random.key(0), cfg)
    assert p["recurrentweights"].shape == (1 + v + v, 4 * v)
    x = jax.nn.one_hot(jax.random.randint(jax.random.key(1), (3, 11), 0, v), v)
    logits = mod.activate(p, cfg, x)
    assert logits.shape == (3, 11, v)

    # BPTT via autodiff: loss decreases on a repeating sequence
    seq = jnp.tile(jnp.arange(v), 3)[: 16 + 1]
    xs = jax.nn.one_hot(seq[:-1], v)[None]
    ys = jax.nn.one_hot(seq[1:], v)[None]
    step = jax.jit(
        lambda p: _sgd(
            p, jax.grad(lambda q: mod.supervised_score(q, cfg, xs, ys))(p), 1.0
        )
    )
    s0 = float(mod.supervised_score(p, cfg, xs, ys))
    for _ in range(100):
        p = step(p)
    s1 = float(mod.supervised_score(p, cfg, xs, ys))
    assert s1 < s0 * 0.5, (s0, s1)


def test_lstm_beam_search_decodes():
    mod = layers.get("lstm")
    v = 6
    cfg = C.LayerConfig(layer_type="lstm", n_in=v, n_out=v)
    p = mod.init(jax.random.key(0), cfg)
    emb = jnp.eye(v)
    beams = mod.beam_search(p, cfg, emb[1], emb, beam_size=3, n_steps=5)
    assert len(beams) <= 3
    for idxs, logp in beams:
        assert all(0 <= i < v for i in idxs)
        assert logp <= 0.0


def test_lstm_device_beam_matches_host_oracle():
    """The scanned device beam search must reproduce the reference-
    shaped host loop (beams, scores, order) — several seeds so parent
    reordering and finished-beam pass-through both get exercised."""
    mod = layers.get("lstm")
    v = 7
    cfg = C.LayerConfig(layer_type="lstm", n_in=v, n_out=v)
    emb = jnp.eye(v)
    for seed in range(4):
        p = mod.init(jax.random.key(seed), cfg)
        dev = mod.beam_search(p, cfg, emb[1], emb, beam_size=3, n_steps=6)
        host = mod.beam_search_host(
            p, cfg, emb[1], emb, beam_size=3, n_steps=6
        )
        assert [i for i, _ in dev] == [i for i, _ in host], (dev, host)
        np.testing.assert_allclose(
            [s for _, s in dev], [s for _, s in host], rtol=1e-5, atol=1e-5
        )


def test_lstm_beam_width1_equals_greedy():
    mod = layers.get("lstm")
    v = 5
    cfg = C.LayerConfig(layer_type="lstm", n_in=v, n_out=v)
    p = mod.init(jax.random.key(3), cfg)
    emb = jnp.eye(v)
    n = 6
    (beam_idxs, beam_lp), = mod.beam_search(
        p, cfg, emb[2], emb, beam_size=1, n_steps=n
    )
    # greedy rollout through the same tick
    h = jnp.zeros((v,))
    c = jnp.zeros((v,))
    y, h, c = mod.tick(p, cfg, emb[2], h, c)
    greedy, lp, prev = [], 0.0, 0
    for _ in range(n):
        y, h, c = mod.tick(p, cfg, emb[prev], h, c)
        logp = jax.nn.log_softmax(y)
        prev = int(jnp.argmax(logp))
        lp += float(logp[prev])
        greedy.append(prev)
        if prev == 0:
            break
    assert beam_idxs == greedy, (beam_idxs, greedy)
    np.testing.assert_allclose(beam_lp, lp, rtol=1e-5, atol=1e-5)
