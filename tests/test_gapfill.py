"""Tests for the parity gap-fill components: PCA, distributed GloVe,
ImageLoader, cloud DataSet iteration, PoS tagging."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.base import DataSet, to_one_hot
from deeplearning4j_tpu.datasets.cloud import (
    CloudDataSetIterator,
    FlakyBucketClient,
    LocalBucketClient,
    RetryingBucketClient,
    TransientStorageError,
    upload_dataset_shards,
)
from deeplearning4j_tpu.datasets.image_loader import ImageLoader
from deeplearning4j_tpu.nlp.pos import PosTagger, rule_tag
from deeplearning4j_tpu.nlp.sentence_iterator import CollectionSentenceIterator
from deeplearning4j_tpu.ops.pca import pca, pca_factor


def test_pca_recovers_dominant_directions():
    rng = np.random.default_rng(0)
    # anisotropic 3D cloud: variance mostly along two known axes
    base = rng.normal(size=(500, 2)) * np.array([10.0, 3.0])
    mix = np.array([[1.0, 0.0, 0.5], [0.0, 1.0, -0.5]])
    x = base @ mix + 0.01 * rng.normal(size=(500, 3))

    proj = pca(x, 2)
    assert proj.shape == (500, 2)
    # top-2 components capture ~all the variance
    total = np.var(x - x.mean(0), axis=0).sum()
    kept = np.var(proj, axis=0).sum()
    assert kept / total > 0.99

    proj2, comps = pca_factor(x, 2)
    assert comps.shape == (2, 3)
    np.testing.assert_allclose(proj, proj2, rtol=1e-5, atol=1e-5)
    # components are orthonormal
    np.testing.assert_allclose(comps @ comps.T, np.eye(2), atol=1e-4)


def test_pca_normalize_and_dim_clip():
    x = np.random.default_rng(1).normal(size=(20, 4))
    proj = pca(x, 10, normalize=True)  # n_dims clipped to D
    assert proj.shape == (20, 4)


def test_tsne_use_pca_path():
    from deeplearning4j_tpu.plot.tsne import Tsne

    x = np.random.default_rng(2).normal(size=(30, 10)).astype(np.float32)
    y = Tsne(n_iter=20, perplexity=5.0, use_pca=True, pca_dims=5).calculate(x)
    assert y.shape == (30, 2)
    assert np.isfinite(y).all()


def _glove_corpus(n):
    pairs = [("ice", "cold"), ("steam", "hot"), ("king", "crown")]
    rng = np.random.default_rng(3)
    out = []
    for _ in range(n):
        a, b = pairs[rng.integers(len(pairs))]
        filler = ["the", "of", "and"][rng.integers(3)]
        out.append(f"{a} {b} {filler} {a} {b}")
    return out

def test_glove_distributed_matches_local_structure(devices):
    from deeplearning4j_tpu.models.glove import Glove
    from deeplearning4j_tpu.parallel.mesh import data_parallel_mesh

    g = Glove(layer_size=16, epochs=8, batch=512, seed=7)
    g.fit_distributed(
        CollectionSentenceIterator(_glove_corpus(150)),
        mesh=data_parallel_mesh(8),
    )
    assert g.loss_history[-1] < g.loss_history[0]
    # co-occurring pair closer than a non-co-occurring one
    assert g.similarity("ice", "cold") > g.similarity("ice", "crown")


def test_image_loader_roundtrip(tmp_path):
    img = np.linspace(0, 255, 28 * 28, dtype=np.float32).reshape(28, 28)
    path = tmp_path / "x.png"
    ImageLoader.to_image(img, path)

    loader = ImageLoader()
    m = loader.as_matrix(path)
    assert m.shape == (28, 28)
    np.testing.assert_allclose(m, img, atol=1.0)

    row = loader.as_row_vector(path)
    assert row.shape == (1, 784)

    resized = ImageLoader(width=14, height=14).as_matrix(path)
    assert resized.shape == (14, 14)

    batches = loader.as_mini_batches(path, 4, 7)
    assert len(batches) == 4 and all(b.shape == (7, 28) for b in batches)


def test_cloud_dataset_iterator_roundtrip(tmp_path):
    rng = np.random.default_rng(4)
    ds = DataSet(
        rng.normal(size=(40, 6)).astype(np.float32),
        to_one_hot(rng.integers(0, 3, 40), 3),
    )
    client = LocalBucketClient(tmp_path / "bucket")
    keys = upload_dataset_shards(client, ds, batch_size=10)
    assert len(keys) == 4

    it = CloudDataSetIterator(client)
    parts = list(it)
    assert len(parts) == 4
    np.testing.assert_allclose(
        np.concatenate([p.features for p in parts]), ds.features, rtol=1e-6
    )

    # reset + preprocessor hook
    it2 = CloudDataSetIterator(
        client, preprocessor=lambda d: DataSet(d.features * 2.0, d.labels)
    )
    first = next(iter(it2))
    np.testing.assert_allclose(first.features, ds.features[:10] * 2.0, rtol=1e-6)
    it2.reset()
    assert it2.has_next()


def test_retrying_client_survives_faults_and_partial_reads(tmp_path):
    """The remote-store hardening the reference delegated to its SDKs:
    transient failures retry with backoff, and a TRUNCATED read is
    caught by the checksum sidecar and retried — the full iterator
    round-trip succeeds against a misbehaving store."""
    rng = np.random.default_rng(5)
    ds = DataSet(
        rng.normal(size=(30, 5)).astype(np.float32),
        to_one_hot(rng.integers(0, 2, 30), 2),
    )
    naps = []
    # writer: transient put failures absorbed by retries
    store = LocalBucketClient(tmp_path / "b")
    writer = RetryingBucketClient(
        FlakyBucketClient(store, fail_times=2), sleep=naps.append
    )
    keys = upload_dataset_shards(writer, ds, batch_size=10)
    assert len(keys) == 3
    assert len(naps) >= 2  # backoff actually engaged

    # reader A: truncation ONLY (no connection faults) — the sidecar
    # checksum is the thing that must catch the half-read and drive the
    # retry (with connection faults mixed in, the retry could be
    # triggered by the fault instead and mask a broken checksum path)
    reader_a = RetryingBucketClient(
        FlakyBucketClient(store, fail_times=0, truncate_first=True),
        sleep=naps.append,
    )
    before = reader_a.attempts
    parts = list(CloudDataSetIterator(reader_a))
    np.testing.assert_allclose(
        np.concatenate([p.features for p in parts]), ds.features, rtol=1e-6
    )
    # each key's first get was truncated -> checksum retry happened
    assert reader_a.attempts - before >= 2 * len(keys)

    # reader B: connection failures AND truncation together
    reader = RetryingBucketClient(
        FlakyBucketClient(store, fail_times=1, truncate_first=True),
        sleep=naps.append,
    )
    assert reader.list_keys() == keys  # sidecars hidden
    parts = list(CloudDataSetIterator(reader))
    np.testing.assert_allclose(
        np.concatenate([p.features for p in parts]), ds.features, rtol=1e-6
    )

    # retries are BOUNDED: a permanently-failing store surfaces the error
    dead = RetryingBucketClient(
        FlakyBucketClient(store, fail_times=99), retries=2,
        sleep=naps.append,
    )
    with pytest.raises(ConnectionError):
        dead.get(keys[0])

    # a permanently-corrupt object (no flakiness, real bad bytes) is a
    # TransientStorageError after exhausting retries, not silent junk
    store.put(keys[0], b"garbage-not-the-original")
    corrupt = RetryingBucketClient(store, retries=1, sleep=naps.append)
    with pytest.raises(TransientStorageError, match="checksum"):
        corrupt.get(keys[0])


def test_pos_rule_backoff():
    assert rule_tag("the") == "DET"
    assert rule_tag("running") == "VERB"
    assert rule_tag("quickly") == "ADV"
    assert rule_tag("42") == "NUM"


def test_pos_untrained_uses_rules():
    tagger = PosTagger()
    tags = dict(tagger.tag(["the", "dog", "runs", "quickly"]))
    assert tags["the"] == "DET"
    assert tags["quickly"] == "ADV"


def test_pos_hmm_disambiguates_by_context():
    # "can" is MD (modal) before a verb, NOUN after a determiner
    corpus = []
    for _ in range(20):
        corpus.append([("i", "PRON"), ("can", "MD"), ("swim", "VERB")])
        corpus.append([("the", "DET"), ("can", "NOUN"), ("fell", "VERB")])
        corpus.append([("you", "PRON"), ("can", "MD"), ("run", "VERB")])
        corpus.append([("a", "DET"), ("can", "NOUN"), ("sat", "VERB")])
    tagger = PosTagger()
    tagger.fit(corpus)
    assert tagger.tag(["i", "can", "swim"])[1][1] == "MD"
    assert tagger.tag(["the", "can", "fell"])[1][1] == "NOUN"
    # OOV word between seen context still decodes
    tagged = tagger.tag(["the", "zzzgadget", "fell"])
    assert len(tagged) == 3
