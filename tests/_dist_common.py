"""Shapes and seeds shared by the 2-process distributed worker and the
in-test single-process reference runs.

The cross-process TP/FSDP/MoE assertions compare losses between
`distributed_worker.py` and `test_distributed_multiprocess.py`; both
sides MUST train the identical program, so the config/seed/batch
literals live here once. (Both import sites resolve this module from
the tests directory: the worker runs as a script from it, and pytest
puts non-package test dirs on sys.path.)
"""

#: tiny transformer used by the cross-process TP / FSDP / MoE checks
TINY_TRANSFORMER = dict(
    vocab_size=32, d_model=16, n_heads=2, n_layers=2, d_ff=32, max_len=16,
)
#: param-init key and token-batch rng seed
TRANSFORMER_SEED = 5
#: (batch, seq+1) of the token batch drawn with TRANSFORMER_SEED
TOKENS_SHAPE = (8, 9)
#: experts for the MoE mode — must equal the model-axis size of the
#: (4, 2) mesh both sides build
N_EXPERTS = 2
