"""Distributed-path tests on the 8-virtual-device CPU mesh — the
fake-cluster technique ≙ reference BaseTestDistributed / IRUnitDriver /
Spark local[8] (SURVEY §4.3)."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.datasets import fetchers
from deeplearning4j_tpu.models import MultiLayerNetwork
from deeplearning4j_tpu.models.lenet import build_lenet, lenet_loss
from deeplearning4j_tpu.nn import conf as C
from deeplearning4j_tpu.parallel import (
    DataParallelTrainer,
    data_parallel_mesh,
    local_sgd_step,
)
from deeplearning4j_tpu.parallel import checkpoint as ckpt
from deeplearning4j_tpu.parallel.cluster import ClusterService, FileRegistry


def _small_mlp():
    mc = C.list_builder(
        C.LayerConfig(activation="tanh"), sizes=[16], n_in=8, n_out=3,
        pretrain=False, backward=True,
    )
    net = MultiLayerNetwork(mc, seed=0)
    params = net.init()

    def loss(params, x, y, key=None):
        return net.supervised_score_fn(params, x, y)

    return net, params, loss


def _toy_batch(n=64, d=8, k=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=(d, k))
    y = np.eye(k, dtype=np.float32)[(x @ w).argmax(1)]
    return jnp.asarray(x), jnp.asarray(y)


def test_data_parallel_trainer_reduces_loss(devices):
    net, params, loss = _small_mlp()
    mesh = data_parallel_mesh(8)
    trainer = DataParallelTrainer(loss, mesh=mesh)
    state = trainer.init(params)
    x, y = _toy_batch(256)
    x, y = trainer.shard_batch(x, y)
    losses = []
    for i in range(60):
        state, l = trainer.step(state, x, y, jax.random.key(i))
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_data_parallel_matches_single_device():
    """Gradient AllReduce over 8 shards == single-device full batch."""
    net, params, loss = _small_mlp()
    x, y = _toy_batch(64)

    import optax

    opt = optax.sgd(0.1)
    t8 = DataParallelTrainer(loss, mesh=data_parallel_mesh(8), optimizer=opt)
    t1 = DataParallelTrainer(loss, mesh=data_parallel_mesh(1), optimizer=opt)
    s8 = t8.init(params)
    s1 = t1.init(params)
    for i in range(5):
        k = jax.random.key(i)
        s8, l8 = t8.step(s8, *t8.shard_batch(x, y), k)
        s1, l1 = t1.step(s1, *t1.shard_batch(x, y), k)
    assert abs(float(l8) - float(l1)) < 1e-4
    for a, b in zip(jax.tree.leaves(s8.params), jax.tree.leaves(s1.params)):
        assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_run_steps_matches_stepwise_loop(devices):
    """In-graph scan loop (one dispatch) == the same steps dispatched one
    at a time: identical params when fed the same per-step keys."""
    net, params, loss = _small_mlp()
    import optax

    mesh = data_parallel_mesh(8)
    opt = optax.sgd(0.1)
    t_scan = DataParallelTrainer(loss, mesh=mesh, optimizer=opt)
    t_step = DataParallelTrainer(loss, mesh=mesh, optimizer=opt)
    x, y = _toy_batch(64)
    xs, ys = t_scan.shard_batch(x, y)

    n = 7
    root = jax.random.key(42)
    s_scan = t_scan.init(params)
    s_scan, losses = t_scan.run_steps(s_scan, xs, ys, root, n)
    assert losses.shape == (n,)

    s_step = t_step.init(params)
    for k in jax.random.split(root, n):
        s_step, _ = t_step.step(s_step, xs, ys, k)

    assert int(s_scan.step) == int(s_step.step) == n
    for a, b in zip(jax.tree.leaves(s_scan.params), jax.tree.leaves(s_step.params)):
        assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_fit_epoch_over_stacked_minibatches(devices):
    """fit_epoch scans pre-staged minibatches in one compiled program and
    the loss trend matches training on the same stream step by step."""
    net, params, loss = _small_mlp()
    mesh = data_parallel_mesh(8)
    trainer = DataParallelTrainer(loss, mesh=mesh)
    state = trainer.init(params)
    x, y = _toy_batch(512)
    xs = jnp.reshape(x, (8, 64, -1))
    ys = jnp.reshape(y, (8, 64, -1))
    first = None
    for epoch in range(6):
        state, losses = trainer.fit_epoch(state, xs, ys, jax.random.key(epoch))
        if first is None:
            first = float(losses[0])
    assert losses.shape == (8,)
    assert float(losses[-1]) < first * 0.6, (first, float(losses[-1]))


def test_local_sgd_parameter_averaging(devices):
    """Local-SGD mode reproduces parameter-averaging semantics: after the
    averaged step, all devices agree and loss decreases."""
    net, params, loss = _small_mlp()
    mesh = data_parallel_mesh(8)
    step = local_sgd_step(loss, mesh, local_steps=4, lr=0.2)
    x, y = _toy_batch(256)
    l_first = None
    for i in range(20):
        params, l = step(params, x, y, jax.random.key(i))
        if l_first is None:
            l_first = float(l)
    assert float(l) < l_first * 0.7


def test_checkpoint_roundtrip_and_manager(tmp_path):
    net, params, _ = _small_mlp()
    p = ckpt.save(tmp_path / "model.npz", params, {"note": "hi"})
    like = jax.tree.map(jnp.zeros_like, params)
    restored, meta = ckpt.restore(p, like)
    assert meta["note"] == "hi"
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(restored)):
        assert np.allclose(np.asarray(a), np.asarray(b))

    mgr = ckpt.CheckpointManager(tmp_path / "ckpts", keep=2, save_every=2)
    for step in range(1, 9):
        mgr.maybe_save(step, params, {"step": step})
    assert mgr.latest_step() == 8
    assert len(list((tmp_path / "ckpts").glob("ckpt_*.npz"))) == 2
    restored, meta = mgr.restore_latest(like)
    assert meta["step"] == 8


def test_cluster_service_heartbeat_evict_earlystop():
    svc = ClusterService(evict_after=0.2)
    svc.heartbeat("w0")
    svc.heartbeat("w1")
    assert svc.workers() == ["w0", "w1"]
    time.sleep(0.25)
    svc.heartbeat("w1")  # w1 stays fresh
    evicted = svc.evict_stale()
    assert evicted == ["w0"]
    assert svc.workers() == ["w1"]

    svc.patience = 2
    assert not svc.report_loss(1.0)
    assert not svc.report_loss(0.9)
    assert not svc.report_loss(0.95)
    assert svc.report_loss(0.95)  # patience exhausted
    assert svc.early_stop


def test_cluster_rest_api():
    import json
    import urllib.request

    svc = ClusterService()
    svc.heartbeat("worker-a")
    svc.phase = "finetune"
    port = svc.start_rest_api()
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/statetracker/workers") as r:
            assert json.loads(r.read()) == ["worker-a"]
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/statetracker/phase") as r:
            assert json.loads(r.read()) == "finetune"
    finally:
        svc.stop_rest_api()


def test_file_registry_discovery(tmp_path):
    master = FileRegistry(tmp_path, "job1")
    master.register_master({"coordinator": "host:1234"})
    worker = FileRegistry(tmp_path, "job1")
    conf = worker.retrieve_master(timeout=2)
    assert conf["coordinator"] == "host:1234"
    worker.register_worker("w0", {"devices": 8})
    assert master.list_workers() == ["w0"]


def test_lenet_trains_data_parallel(devices):
    """Flagship model one full DP step on the 8-device mesh + loss drop."""
    net, params = build_lenet(seed=0)
    loss = lenet_loss(net)
    mesh = data_parallel_mesh(8)
    trainer = DataParallelTrainer(loss, mesh=mesh)
    state = trainer.init(params)
    ds = fetchers.mnist(n=256)
    x = jnp.asarray(ds.features)
    y = jnp.asarray(ds.labels)
    x, y = trainer.shard_batch(x, y)
    l0 = None
    for i in range(12):
        state, l = trainer.step(state, x, y, jax.random.key(i))
        if l0 is None:
            l0 = float(l)
    assert float(l) < l0, (l0, float(l))


def test_gradient_accumulation_matches_full_batch(devices):
    """step_accumulate over n microbatches == one step on the concatenated
    batch (loss is a batch mean, so summed-then-averaged micro-gradients
    reproduce the full-batch gradient exactly)."""
    net, params = build_lenet(seed=0)
    loss = lenet_loss(net)
    mesh = data_parallel_mesh(8)
    ds = fetchers.mnist(n=256)
    x = jnp.asarray(ds.features)
    y = jnp.asarray(ds.labels)

    t1 = DataParallelTrainer(loss, mesh=mesh, donate=False)
    s1 = t1.init(params)
    xs, ys = t1.shard_batch(x, y)
    s1, l_full = t1.step(s1, xs, ys, jax.random.key(0))

    t2 = DataParallelTrainer(loss, mesh=mesh, donate=False)
    s2 = t2.init(params)
    xm = x.reshape(4, 64, -1)
    ym = y.reshape(4, 64, -1)
    s2, l_acc = t2.step_accumulate(s2, xm, ym, jax.random.key(0))

    np.testing.assert_allclose(float(l_full), float(l_acc), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        )
