"""Worker process for the 2-process jax.distributed test.

Run as: python tests/distributed_worker.py <registry_addr> <job_id> <pid> <nprocs>

Boot sequence (≙ the reference's DeepLearning4jDistributed bootstrap,
DeepLearning4jDistributed.java:48, with ZooKeeper discovery
≙ ZooKeeperConfigurationRegister.java:40):
- process 0 registers the jax.distributed coordinator address in the
  network registry; the other processes retrieve it — the ONLY shared
  state is the registry address (no shared filesystem);
- every process calls jax.distributed.initialize and registers itself as
  an (ephemeral) worker;
- all processes run the same SPMD program: a DataParallelTrainer step
  over the global (nprocs x local_devices) mesh;
- each prints its final loss as LOSS=<float> for the test to compare.

The device topology is pinned BEFORE jax import: 4 virtual CPU devices
per process, so 2 processes reproduce the 8-device mesh the
single-process suite uses.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    .replace("--xla_force_host_platform_device_count=8", "")
    + " --xla_force_host_platform_device_count=4"
).strip()

import numpy as np  # noqa: E402


def main() -> int:
    registry_addr, job_id, pid_s, nprocs_s = sys.argv[1:5]
    pid, nprocs = int(pid_s), int(nprocs_s)

    import jax

    jax.config.update("jax_platforms", "cpu")
    from jax._src import xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)

    # NO persistent compile cache here, deliberately: under
    # jax.distributed the cache's cross-process write coordination
    # deadlocked the 2-process bring-up (worker hung until the 420s
    # test timeout — measured). Only the pytest process itself caches
    # (conftest); every subprocess worker runs uncached.

    from deeplearning4j_tpu.parallel.registry import NetworkRegistry

    reg = NetworkRegistry(registry_addr, job_id)
    if pid == 0:
        # the coordinator picks a free port and publishes it
        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        coordinator = f"127.0.0.1:{port}"
        reg.register_master({"coordinator": coordinator, "nprocs": nprocs})
    else:
        coordinator = reg.retrieve_master(timeout=60.0)["coordinator"]

    from deeplearning4j_tpu.parallel.cluster import initialize_distributed

    initialize_distributed(
        coordinator=coordinator, num_processes=nprocs, process_id=pid
    )
    reg.register_worker(str(pid), {"devices": jax.local_device_count()})

    assert jax.device_count() == 4 * nprocs, jax.device_count()
    assert jax.process_count() == nprocs

    # the same tiny MLP training run as the single-process reference in
    # the test — identical seeds, identical global batch
    import jax.numpy as jnp
    import optax

    from deeplearning4j_tpu.parallel import DataParallelTrainer
    from deeplearning4j_tpu.parallel import mesh as mesh_lib

    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 8)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 32)]
    w_rng = np.random.default_rng(1)
    params = {
        "w1": jnp.asarray(w_rng.normal(size=(8, 16)).astype(np.float32) * 0.3),
        "b1": jnp.zeros((16,)),
        "w2": jnp.asarray(w_rng.normal(size=(16, 4)).astype(np.float32) * 0.3),
        "b2": jnp.zeros((4,)),
    }

    def loss_fn(p, xb, yb, key=None):
        h = jnp.tanh(xb @ p["w1"] + p["b1"])
        logits = h @ p["w2"] + p["b2"]
        return optax.softmax_cross_entropy(logits, yb).mean()

    mesh = mesh_lib.data_parallel_mesh(jax.device_count())
    trainer = DataParallelTrainer(
        loss_fn, mesh=mesh, optimizer=optax.sgd(0.1)
    )
    state = trainer.init(params)
    xs, ys = trainer.shard_global_batch(x, y)
    loss = None
    for i in range(20):
        state, loss = trainer.step(state, xs, ys, jax.random.key(0))
    print(f"WORKERS={','.join(reg.list_workers())}", flush=True)
    print(f"LOSS={float(loss):.10f}", flush=True)

    if len(sys.argv) > 5:
        # multi-process orbax round-trip: every process writes only the
        # shards it owns (the npz manager cannot address a multi-process
        # mesh — the regime AsyncShardedCheckpointManager exists for)
        from deeplearning4j_tpu.parallel.checkpoint import (
            AsyncShardedCheckpointManager,
        )

        mgr = AsyncShardedCheckpointManager(sys.argv[5], save_every=1)
        mgr.maybe_save(20, state.params, {"loss": float(loss)})
        mgr.wait()
        restored, meta = mgr.restore_latest(state.params)
        ok = all(
            bool(jnp.all(a == b))
            for a, b in zip(
                jax.tree.leaves(restored), jax.tree.leaves(state.params)
            )
        ) and int(meta["step"]) == 20
        print(f"ORBAX={'ok' if ok else 'MISMATCH'}", flush=True)

    # cross-process tensor parallelism: build a (dp=4, tp=2) mesh whose
    # TP pairs SPAN the process boundary (device i paired with i+4, i.e.
    # one device from each process), so the Megatron layout's psum runs
    # over the host-to-host transport — the regime a real multi-host TPU
    # pod exercises over DCN. ≙ the reference's cross-JVM parameter
    # traffic, now an in-graph collective.
    from jax.sharding import Mesh

    from deeplearning4j_tpu.models.transformer import (
        TransformerConfig, transformer_train_step,
    )
    from deeplearning4j_tpu.parallel import mesh as mesh_lib

    if nprocs != 2:
        # the cross-process pairing below is written for exactly 2
        # processes; other topologies skip the TP check cleanly
        return 0
    devs = jax.devices()
    local = jax.local_device_count()
    grid = np.array(
        [[devs[i], devs[i + local]] for i in range(local)], dtype=object
    )
    tmesh = Mesh(grid, (mesh_lib.DATA_AXIS, mesh_lib.MODEL_AXIS))
    from _dist_common import (
        N_EXPERTS, TINY_TRANSFORMER, TOKENS_SHAPE, TRANSFORMER_SEED,
    )

    tcfg = TransformerConfig(**TINY_TRANSFORMER)
    tstep, tinit, tshard = transformer_train_step(tmesh, tcfg)
    tparams, topt = tinit(jax.random.key(TRANSFORMER_SEED))
    toks_np = (
        np.random.default_rng(TRANSFORMER_SEED)
        .integers(0, tcfg.vocab_size, TOKENS_SHAPE)
        .astype(np.int32)
    )
    ttoks = tshard(toks_np)
    tl = None
    for _ in range(3):
        tparams, topt, tl = tstep(tparams, topt, ttoks)
    print(f"TPLOSS={float(tl):.10f}", flush=True)

    # ZeRO-3/FSDP across the process boundary: params + optimizer state
    # shard over the data axis (whose groups span both processes), so
    # the per-step all-gathers and reduce-scatters ride the host-to-host
    # transport — the DCN regime of a multi-slice pod.
    fstep, finit, fshard = transformer_train_step(tmesh, tcfg, fsdp=True)
    fparams, fopt = finit(jax.random.key(TRANSFORMER_SEED))
    ftoks = fshard(toks_np)
    fl = None
    for _ in range(3):
        fparams, fopt, fl = fstep(fparams, fopt, ftoks)
    print(f"FSDPLOSS={float(fl):.10f}", flush=True)

    # MoE/EP across the process boundary: experts live one-per-device on
    # the model axis, whose pairs span the two processes — the token
    # all-to-all dispatch/combine crosses hosts.
    import dataclasses

    # field-for-field identical to tcfg apart from the experts — the
    # MOELOSS comparison against the single-process reference depends
    # on the two configs never drifting
    mcfg = dataclasses.replace(tcfg, n_experts=N_EXPERTS)
    mstep, minit, mshard = transformer_train_step(tmesh, mcfg)
    mparams, mopt = minit(jax.random.key(TRANSFORMER_SEED))
    mtoks = mshard(toks_np)
    ml = None
    for _ in range(3):
        mparams, mopt, ml = mstep(mparams, mopt, mtoks)
    print(f"MOELOSS={float(ml):.10f}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
