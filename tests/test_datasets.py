"""Data-pipeline tests ≙ reference CSVDataSetIteratorTest / DataSetTest +
fetcher behaviors."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets import (
    BaseDatasetIterator,
    DataSet,
    ListDataSetIterator,
    MultipleEpochsIterator,
    ReconstructionDataSetIterator,
    SamplingDataSetIterator,
    TestDataSetIterator,
)
from deeplearning4j_tpu.datasets import fetchers
from deeplearning4j_tpu.datasets.base import to_one_hot
from deeplearning4j_tpu.datasets.iterators import ShardedDataSetIterator, moving_window


def test_dataset_basics():
    ds = DataSet(np.arange(20, dtype=np.float32).reshape(10, 2), to_one_hot(np.arange(10) % 3, 3))
    assert ds.num_examples() == 10
    assert ds.num_inputs() == 2
    assert ds.num_outcomes() == 3
    train, test = ds.split_test_and_train(7)
    assert train.num_examples() == 7 and test.num_examples() == 3
    shuffled = ds.shuffle(0)
    assert sorted(shuffled.features[:, 0].tolist()) == ds.features[:, 0].tolist()


def test_one_hot():
    oh = to_one_hot(np.array([0, 2, 1]), 3)
    assert oh.shape == (3, 3)
    assert (oh.argmax(1) == [0, 2, 1]).all()


def test_iris_fetcher():
    f = fetchers.IrisDataFetcher()
    assert f.total_examples() == 150
    assert f.input_columns() == 4
    assert f.total_outcomes() == 3
    batch = f.fetch(10)
    assert batch.features.shape == (10, 4)
    it = BaseDatasetIterator(30, None, f)
    batches = list(it)
    assert len(batches) == 5
    assert all(b.num_examples() == 30 for b in batches)


def test_mnist_synthetic_fallback_and_idx_reader(tmp_path):
    ds = fetchers.mnist(train=True, n=256)
    assert ds.features.shape == (256, 784)
    assert ds.labels.shape == (256, 10)
    assert 0 <= ds.features.min() and ds.features.max() <= 1

    # synthetic classes must be separable by a trivial nearest-centroid rule
    feats, labels = ds.features, ds.labels.argmax(1)
    centroids = np.stack([feats[labels == c].mean(0) for c in range(10)])
    pred = ((feats[:, None, :] - centroids[None]) ** 2).sum(-1).argmin(1)
    assert (pred == labels).mean() > 0.9

    # idx round-trip
    import struct

    imgs = (ds.features[:16].reshape(16, 28, 28) * 255).astype(np.uint8)
    p = tmp_path / "imgs-idx3-ubyte"
    with open(p, "wb") as fh:
        fh.write(struct.pack(">HBB", 0, 0x08, 3))
        fh.write(struct.pack(">III", 16, 28, 28))
        fh.write(imgs.tobytes())
    back = fetchers._read_idx(p)
    assert back.shape == (16, 28, 28)
    assert (back == imgs).all()


def test_csv_fetcher(tmp_path):
    p = tmp_path / "data.csv"
    rows = ["1.0,2.0,0", "3.0,4.0,1", "5.0,6.0,2", "7.0,8.0,0"]
    p.write_text("\n".join(rows))
    ds = fetchers.csv(p, label_column=2)
    assert ds.features.shape == (4, 2)
    assert ds.labels.shape == (4, 3)


def test_lfw_synthetic():
    ds = fetchers.lfw(n=50)
    assert ds.features.shape[0] == 50
    assert ds.labels is not None


def test_sampling_and_reconstruction_iterators():
    ds = DataSet(np.random.default_rng(0).normal(size=(100, 5)).astype(np.float32),
                 to_one_hot(np.zeros(100), 2))
    s = SamplingDataSetIterator(ds, batch_size=8, total_batches=3)
    batches = list(s)
    assert len(batches) == 3 and batches[0].features.shape == (8, 5)

    r = ReconstructionDataSetIterator(ListDataSetIterator(ds, 25))
    for b in r:
        assert (b.labels == b.features).all()
    assert r.total_outcomes() == 5


def test_multiple_epochs_and_test_iterator():
    ds = DataSet(np.ones((10, 2), dtype=np.float32))
    inner = TestDataSetIterator(ListDataSetIterator(ds, 5))
    it = MultipleEpochsIterator(3, inner)
    assert len(list(it)) == 6
    assert inner.batches_served == 6
    assert inner.resets == 3


def test_sharded_iterator_partitions_batches():
    ds = DataSet(np.arange(80, dtype=np.float32).reshape(40, 2))
    shards = [
        list(ShardedDataSetIterator(ListDataSetIterator(ds, 4), shard=s, num_shards=2))
        for s in range(2)
    ]
    assert len(shards[0]) == 5 and len(shards[1]) == 5
    seen = np.concatenate(
        [b.features for b in shards[0]] + [b.features for b in shards[1]]
    )
    assert sorted(seen[:, 0].tolist()) == ds.features[:, 0].tolist()


def test_moving_window():
    m = np.arange(16).reshape(4, 4)
    w = moving_window(m, 2, 2)
    assert w.shape == (9, 2, 2)
    assert (w[0] == [[0, 1], [4, 5]]).all()


def test_curves():
    ds = fetchers.curves(n=10, dim=100)
    assert ds.features.shape == (10, 100)
    assert ds.labels is None


def test_prefetch_dataset_iterator():
    """Native prefetch pipeline behind the DataSetIterator protocol."""
    import numpy as np

    from deeplearning4j_tpu.datasets.iterators import PrefetchDataSetIterator

    rng = np.random.default_rng(0)
    feats = rng.integers(0, 256, (40, 5), dtype=np.uint8)
    labels = rng.integers(0, 4, 40, dtype=np.uint8)
    it = PrefetchDataSetIterator(feats, labels, num_classes=4, batch_size=10, seed=1)
    try:
        assert it.input_columns() == 5 and it.total_outcomes() == 4
        batches = list(it)
        assert len(batches) == 4
        for ds in batches:
            assert ds.features.shape == (10, 5)
            assert ds.labels.shape == (10, 4)
            assert np.all(ds.labels.sum(1) == 1.0)
        # second pass yields a different (reshuffled-stream) order overall
        flat1 = np.concatenate([d.features for d in batches])
        flat2 = np.concatenate([d.features for d in it])
        assert flat1.shape == flat2.shape
    finally:
        it.close()
