"""Optimizer-stack tests: every solver minimizes a quadratic and a small
least-squares problem; updater semantics; line search; listeners.
(The reference has no optimizer unit tests at all — SURVEY §4 gap.)"""

import jax
import jax.numpy as jnp
import pytest

from deeplearning4j_tpu.nn.conf import LayerConfig, OptimizationAlgorithm
from deeplearning4j_tpu.optimize import Solver, updaters
from deeplearning4j_tpu.optimize.api import (
    ModelFunctions,
    ScoreIterationListener,
)
from deeplearning4j_tpu.optimize import linesearch
from deeplearning4j_tpu.utils import tree_math as tm


def _quadratic_model():
    # f(p) = 0.5*(p-c)'A(p-c) over a dict pytree
    A = jnp.diag(jnp.array([1.0, 10.0, 0.5, 4.0]))
    c = jnp.array([1.0, -2.0, 3.0, 0.5])

    def score(params, key=None):
        d = params["x"] - c
        return 0.5 * d @ A @ d

    return ModelFunctions.from_score(score), c


def _lsq_model(key):
    # least squares ||Xw - y||^2 with forward/loss split for HF
    kx, kw = jax.random.split(key)
    X = jax.random.normal(kx, (64, 8))
    w_true = jax.random.normal(kw, (8,))
    y = X @ w_true

    def forward(params):
        return X @ params["w"]

    def loss_on_outputs(z):
        return 0.5 * jnp.mean((z - y) ** 2)

    def score(params, key=None):
        return loss_on_outputs(forward(params))

    return (
        ModelFunctions.from_score(
            score, forward=forward, loss_on_outputs=loss_on_outputs
        ),
        w_true,
    )


ALGOS = [
    OptimizationAlgorithm.GRADIENT_DESCENT,
    OptimizationAlgorithm.ITERATION_GRADIENT_DESCENT,
    OptimizationAlgorithm.CONJUGATE_GRADIENT,
    OptimizationAlgorithm.LBFGS,
    OptimizationAlgorithm.HESSIAN_FREE,
]


@pytest.mark.parametrize("algo", ALGOS)
def test_solvers_minimize_quadratic(algo):
    model, c = _quadratic_model()
    conf = LayerConfig(
        optimization_algo=algo,
        num_iterations=150,
        lr=0.05,
        use_adagrad=False,
        momentum=0.0,
        num_line_search_iterations=8,
    )
    params = {"x": jnp.zeros(4)}
    solver = Solver(conf, model)
    out, score = solver.optimize(params, jax.random.key(0))
    assert score < 0.05, (algo, score)


@pytest.mark.parametrize(
    "algo",
    [
        OptimizationAlgorithm.CONJUGATE_GRADIENT,
        OptimizationAlgorithm.LBFGS,
        OptimizationAlgorithm.HESSIAN_FREE,
    ],
)
def test_second_order_solvers_on_least_squares(algo):
    model, w_true = _lsq_model(jax.random.key(1))
    conf = LayerConfig(
        optimization_algo=algo,
        num_iterations=100,
        use_adagrad=False,
        momentum=0.0,
        lr=0.1,
        num_line_search_iterations=10,
    )
    params = {"w": jnp.zeros(8)}
    out, score = Solver(conf, model).optimize(params, jax.random.key(2))
    assert score < 1e-3, (algo, score)
    assert jnp.max(jnp.abs(out["w"] - w_true)) < 0.2


def test_hessian_free_converges_fast_on_illconditioned():
    """HF should crack an ill-conditioned quadratic in few iterations."""
    model, c = _quadratic_model()
    conf = LayerConfig(
        optimization_algo=OptimizationAlgorithm.HESSIAN_FREE,
        num_iterations=20,
        use_adagrad=False,
    )
    out, score = Solver(conf, model).optimize({"x": jnp.zeros(4)}, jax.random.key(0))
    assert score < 1e-4
    assert jnp.allclose(out["x"], c, atol=0.05)


def test_line_search_backtracks_on_overshoot():
    def score_fn(p):
        return jnp.sum(p["x"] ** 2)

    params = {"x": jnp.array([1.0, 1.0])}
    grad = {"x": jnp.array([2.0, 2.0])}
    direction = {"x": jnp.array([-20.0, -20.0])}  # way overshooting
    res = linesearch.backtrack(score_fn, params, direction, grad, max_iterations=10)
    assert 0 < float(res.step) < 1.0
    assert float(res.score) < score_fn(params)


def test_updater_adagrad_and_momentum_schedule():
    conf = LayerConfig(
        use_adagrad=True, lr=0.1, momentum=0.5, momentum_after={5: 0.9}
    )
    params = {"w": jnp.ones(3)}
    grads = {"w": jnp.ones(3)}
    state = updaters.init(params)
    step1, state = updaters.adjust(conf, state, grads, params)
    # adagrad first step: lr * g / sqrt(g^2) = lr
    assert jnp.allclose(step1["w"], 0.1, atol=1e-4)
    assert int(state.iteration) == 1
    # momentum schedule kicks in at iteration 5
    assert float(updaters._momentum_at(conf, jnp.asarray(4))) == pytest.approx(0.5)
    assert float(updaters._momentum_at(conf, jnp.asarray(5))) == pytest.approx(0.9)


def test_updater_unit_norm_constraint():
    conf = LayerConfig(
        use_adagrad=False, lr=1.0, momentum=0.0, constrain_gradient_to_unit_norm=True
    )
    params = {"w": jnp.zeros(4)}
    grads = {"w": jnp.full((4,), 3.0)}
    step, _ = updaters.adjust(conf, updaters.init(params), grads, params)
    assert jnp.allclose(tm.norm2(step), 1.0, atol=1e-5)


def test_listeners_receive_scores():
    model, _ = _quadratic_model()
    conf = LayerConfig(
        optimization_algo=OptimizationAlgorithm.GRADIENT_DESCENT,
        num_iterations=10,
        use_adagrad=False,
        momentum=0.0,
    )
    listener = ScoreIterationListener(print_every=100)
    Solver(conf, model, listeners=[listener]).optimize(
        {"x": jnp.zeros(4)}, jax.random.key(0)
    )
    assert len(listener.history) > 0
    assert listener.history[-1] <= listener.history[0]


def test_termination_stops_early():
    model, c = _quadratic_model()
    conf = LayerConfig(
        optimization_algo=OptimizationAlgorithm.HESSIAN_FREE,
        num_iterations=1000,
        use_adagrad=False,
    )
    from deeplearning4j_tpu.optimize import solvers as S

    params, score, iters = S.optimize_jit(conf, model, {"x": jnp.zeros(4)}, jax.random.key(0))
    assert int(iters) < 1000  # eps termination fired
    assert float(score) < 1e-4
