"""Round-3 parity dots: generic RecordReader bridge, provisioning
executor, questions-words analogy report."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.records import (
    CSVRecordReader,
    ImageRecordReader,
    RecordReaderDataSetIterator,
    SVMLightRecordReader,
)
from deeplearning4j_tpu.utils.provision import (
    ClusterSetup,
    ClusterSpec,
    CommandResult,
    HostProvisioner,
    ProvisionError,
    RecordingRunner,
)


# -- RecordReader bridge (≙ RecordReaderDataSetIterator.java:48) -------------

def test_csv_record_reader_batches_and_one_hot(tmp_path):
    p = tmp_path / "data.csv"
    p.write_text(
        "f1,f2,label\n"
        "1.0,2.0,0\n"
        "3.0,4.0,1\n"
        "5.0,6.0,2\n"
        "7.0,8.0,1\n"
        "9.0,10.0,0\n"
    )
    it = RecordReaderDataSetIterator(
        CSVRecordReader(p, skip_lines=1), batch_size=2,
        label_index=-1, num_classes=3,
    )
    batches = list(it)
    assert [len(b.features) for b in batches] == [2, 2, 1]  # short tail
    np.testing.assert_array_equal(
        batches[0].features, [[1.0, 2.0], [3.0, 4.0]]
    )
    np.testing.assert_array_equal(
        batches[0].labels, [[1, 0, 0], [0, 1, 0]]
    )
    # reset() rewinds (≙ the DataSetIterator contract)
    it.reset()
    again = next(iter(it))
    np.testing.assert_array_equal(again.features, batches[0].features)


def test_csv_label_column_in_middle_and_unsupervised(tmp_path):
    p = tmp_path / "mid.csv"
    p.write_text("1,1,9\n0,2,8\n")
    b = next(iter(RecordReaderDataSetIterator(
        CSVRecordReader(p), batch_size=2, label_index=0, num_classes=2,
    )))
    np.testing.assert_array_equal(b.features, [[1, 9], [2, 8]])
    np.testing.assert_array_equal(b.labels, [[0, 1], [1, 0]])
    # unsupervised: labels mirror features (the reference's
    # labelIndex < 0 branch)
    u = next(iter(RecordReaderDataSetIterator(
        CSVRecordReader(p), batch_size=2, label_index=None,
    )))
    np.testing.assert_array_equal(u.features, u.labels)


def test_label_requires_num_classes():
    with pytest.raises(ValueError, match="num_classes"):
        RecordReaderDataSetIterator(CSVRecordReader("x.csv"), label_index=-1)


def test_svmlight_record_reader(tmp_path):
    p = tmp_path / "s.txt"
    p.write_text(
        "1 1:0.5 3:2.0  # comment\n"
        "0 2:1.5\n"
        "\n"
    )
    b = next(iter(RecordReaderDataSetIterator(
        SVMLightRecordReader(p, n_features=3), batch_size=2,
        label_index=-1, num_classes=2,
    )))
    np.testing.assert_allclose(b.features, [[0.5, 0, 2.0], [0, 1.5, 0]])
    np.testing.assert_array_equal(b.labels, [[0, 1], [1, 0]])


def test_image_record_reader_directory_labels(tmp_path):
    PIL = pytest.importorskip("PIL.Image")
    for cls, shade in (("cats", 40), ("dogs", 200)):
        d = tmp_path / cls
        d.mkdir()
        for i in range(2):
            PIL.new("L", (4, 4), shade + i).save(d / f"{i}.png")
    reader = ImageRecordReader(tmp_path, width=4, height=4)
    assert reader.labels == ["cats", "dogs"]
    b = next(iter(RecordReaderDataSetIterator(
        reader, batch_size=4, label_index=-1, num_classes=2,
    )))
    assert b.features.shape == (4, 16)
    np.testing.assert_array_equal(
        np.argmax(b.labels, -1), [0, 0, 1, 1]
    )
    assert abs(float(b.features[0, 0]) - 40) < 2
    assert abs(float(b.features[2, 0]) - 200) < 2


# -- provisioning executor (≙ ClusterSetup.java:24) ---------------------------

def test_cluster_setup_provisions_master_and_workers(tmp_path):
    script = tmp_path / "setup.sh"
    script.write_text("#!/bin/sh\necho hi\n")
    runner = RecordingRunner()
    spec = ClusterSpec(
        name="dl4j", num_workers=2, zone="us-z",
        worker_script=str(script),
    )
    names = ClusterSetup(spec, runner=runner).provision()
    assert names == ["dl4j-master", "dl4j-worker-0", "dl4j-worker-1"]
    joined = [" ".join(c) for c in runner.commands]
    # 3 creates + per worker (scp + ssh-run)
    assert sum("tpus tpu-vm create" in c for c in joined) == 3
    assert sum("tpus tpu-vm scp" in c for c in joined) == 2
    run_cmds = [c for c in joined if "tpu-vm ssh" in c]
    assert len(run_cmds) == 2
    assert "chmod +x setup.sh && ./setup.sh" in run_cmds[0]
    assert "--zone=us-z" in joined[0]


def test_cluster_setup_teardown_reverses():
    runner = RecordingRunner()
    ClusterSetup(ClusterSpec(num_workers=1), runner=runner).teardown()
    deleted = [c[5] for c in runner.commands]
    assert deleted == ["dl4j-worker-0", "dl4j-master"]


def test_provision_failure_raises_with_command():
    runner = RecordingRunner(responses={
        "create dl4j-worker-0": CommandResult(1, stderr="quota exceeded"),
    })
    with pytest.raises(ProvisionError, match="quota exceeded"):
        ClusterSetup(ClusterSpec(num_workers=1), runner=runner).provision()


def test_host_provisioner_ssh_forms(tmp_path):
    key = tmp_path / "id.pub"
    key.write_text("ssh-ed25519 AAAA me@host\n")
    runner = RecordingRunner()
    # plain-ssh host (the reference's regime)
    hp = HostProvisioner(
        "10.0.0.5", user="ubuntu", key_file="/k", runner=runner
    )
    hp.run_remote_command("ls /")
    hp.upload_for_deployment("/src/a.tar", "/dst/a.tar")
    hp.add_key_file(str(key))
    cmds = [" ".join(c) for c in runner.commands]
    assert cmds[0] == "ssh -i /k ubuntu@10.0.0.5 ls /"
    assert cmds[1] == "scp -i /k /src/a.tar ubuntu@10.0.0.5:/dst/a.tar"
    assert "authorized_keys" in cmds[2]
    # tpu-vm host routes through gcloud
    tp = HostProvisioner("node-1", zone="z", tpu_vm=True, runner=runner)
    tp.run_remote_command("hostname")
    assert runner.commands[-1][:6] == [
        "gcloud", "compute", "tpus", "tpu-vm", "ssh", "node-1",
    ]


# -- questions-words analogy report (≙ WordVectorsImpl accuracy) -------------

def test_questions_words_parse_and_report(tmp_path):
    from deeplearning4j_tpu.models.word2vec import parse_questions_words

    qw = tmp_path / "questions-words.txt"
    qw.write_text(
        ": capital-common-countries\n"
        "athens greece paris france\n"
        "paris france athens greece\n"
        ": family\n"
        "king queen man woman\n"
        "king queen oov1 oov2\n"
        "not four tokens here really extra\n"
    )
    cats = parse_questions_words(qw)
    assert set(cats) == {"capital-common-countries", "family"}
    assert len(cats["capital-common-countries"]) == 2
    assert cats["family"][0] == ("king", "queen", "man", "woman")

    # a vocabulary engineered so the analogies resolve exactly:
    # vec(b) - vec(a) + vec(c) == vec(d) by construction
    class _FakeCache:
        def __init__(self, words):
            self._w = list(words)

        def index_of(self, w):
            return self._w.index(w) if w in self._w else -1

        def word_for(self, i):
            return self._w[i]

        def __contains__(self, w):
            return w in self._w

    from deeplearning4j_tpu.models.word2vec import Word2Vec

    words = ["athens", "greece", "paris", "france",
             "king", "queen", "man", "woman"]
    base = np.eye(4, dtype=np.float32)  # country-ness, city-ness axes
    vecs = {
        "athens": base[0], "greece": base[0] + base[1],
        "paris": base[2], "france": base[2] + base[1],
        "king": base[0] * 2, "queen": base[0] * 2 + base[3],
        "man": base[2] * 2, "woman": base[2] * 2 + base[3],
    }
    w2v = Word2Vec.__new__(Word2Vec)
    w2v.cache = _FakeCache(words)
    w2v.syn0 = np.stack([vecs[w] for w in words])
    report = w2v.accuracy_report(qw)
    assert report["capital-common-countries"]["accuracy"] == 1.0
    assert report["family"]["correct"] == 1
    assert report["family"]["skipped"] == 1  # the OOV question
    assert report["TOTAL"]["total"] == 3
    assert report["TOTAL"]["accuracy"] == 1.0
