"""Chaos suite for the serving engine's fault-tolerance layer.

The load-bearing property mirrors ``test_serving.py``'s: byte-identical
greedy streams — but now UNDER INJECTED FAULTS. Because greedy decode
is deterministic and everything the device holds is a pure function of
host state (prompt + tokens decoded so far), a transient fault retried
at a boundary, and even a full engine crash recovered by replay
(re-prefill + teacher-forced token replay), must reproduce exactly the
streams an unfaulted engine produces. Every fault here is scripted
through :class:`FaultInjector` at pinned boundary indices, so the suite
is deterministic — no sleeps-and-hope.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.models.transformer import (
    TransformerConfig,
    init_transformer,
)
from deeplearning4j_tpu.serving import (
    EngineCrash,
    FaultInjector,
    Request,
    RequestScheduler,
    RequestStatus,
    ServingEngine,
    ServingServer,
    run_request_trace,
)

pytestmark = pytest.mark.chaos

CFG = TransformerConfig(
    vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64, max_len=32
)
_PARAMS = {}


def _params(seed=0):
    if seed not in _PARAMS:
        _PARAMS[seed] = init_transformer(jax.random.key(seed), CFG)
    return _PARAMS[seed]


def _requests(n, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        tp = int(rng.integers(3, 10))
        out.append(Request(
            prompt=rng.integers(0, CFG.vocab_size, (tp,)).astype(np.int32),
            max_new=int(rng.integers(4, 12)),
        ))
    return out


@pytest.fixture(autouse=True)
def _lock_sanitized():
    """The whole chaos suite runs under the LockSanitizer: every
    engine/server/scheduler lock built inside a test is order- and
    lockset-tracked across the fault-injection/recovery paths, and any
    inversion or unlocked cross-thread write fails the test that
    provoked it."""
    from deeplearning4j_tpu.analysis.sanitizers import LockSanitizer

    with LockSanitizer() as san:
        yield san
    san.assert_clean()


def _clone(reqs):
    """Same prompts/budgets, fresh ids/state — for a faulted re-run."""
    return [Request(prompt=r.prompt.copy(), max_new=r.max_new) for r in reqs]


def _run_clean(reqs, n_slots=2):
    engine = ServingEngine(CFG, _params(), n_slots=n_slots, temperature=0.0)
    for r in reqs:
        engine.submit(r)
    return engine.run()


def _fast_engine(faults, n_slots=2, **kw):
    return ServingEngine(
        CFG, _params(), n_slots=n_slots, temperature=0.0, faults=faults,
        retry_backoff_s=0.001, max_backoff_s=0.004, **kw,
    )


def _assert_parity(clean_reqs, clean, faulted_reqs, faulted):
    for a, b in zip(clean_reqs, faulted_reqs):
        np.testing.assert_array_equal(clean[a.id], faulted[b.id])


# -- supervised retries + replay recovery --------------------------------


def test_transient_faults_byte_identical_parity():
    """Transient faults at step AND prefill boundaries mid-stream:
    retried with backoff, token streams byte-identical to an unfaulted
    engine, and the retries are visible in the metrics."""
    reqs = _requests(6, seed=7)
    clean = _run_clean(reqs)

    reqs2 = _clone(reqs)
    inj = (FaultInjector()
           .plan("step", at=2, kind="transient")
           .plan("step", at=6, kind="transient")
           .plan("prefill", at=1, kind="transient"))
    engine = _fast_engine(inj)
    for r in reqs2:
        engine.submit(r)
    faulted = engine.run()

    _assert_parity(reqs, clean, reqs2, faulted)
    assert engine.metrics.n_retries == 3
    assert engine.metrics.n_restarts == 0
    assert all(r.status is RequestStatus.FINISHED for r in reqs2)


def test_engine_crash_recovers_via_replay_zero_dropped():
    """An engine-loop crash with slots mid-decode at mixed depths and
    requests still queued: recover() rebuilds device state by replay
    and every stream finishes byte-identical — zero dropped requests."""
    reqs = _requests(8, seed=3)
    clean = _run_clean(reqs)

    reqs2 = _clone(reqs)
    inj = (FaultInjector()
           .plan("step", at=5, kind="crash")
           .plan("step", at=11, kind="crash"))  # crash twice for spite
    engine = _fast_engine(inj)
    for r in reqs2:
        engine.submit(r)
    faulted = engine.run()

    assert len(faulted) == len(clean) == len(reqs)
    _assert_parity(reqs, clean, reqs2, faulted)
    assert engine.metrics.n_restarts == 2


def test_persistent_transient_escalates_to_replay():
    """A transient fault that outlives the retry budget (no implicated
    request) escalates to EngineCrash; supervision recovers by replay
    and parity still holds."""
    reqs = _requests(4, seed=5)
    clean = _run_clean(reqs)

    reqs2 = _clone(reqs)
    inj = FaultInjector().plan("step", at=1, kind="transient", times=4)
    engine = _fast_engine(inj, max_retries=2)
    for r in reqs2:
        engine.submit(r)
    faulted = engine.run()

    _assert_parity(reqs, clean, reqs2, faulted)
    # retry budget burned (3 raises) + the 4th raise post-recovery is
    # retried afresh
    assert engine.metrics.n_retries == 4
    assert engine.metrics.n_restarts == 1


def test_unsupervised_crash_propagates():
    """run(max_restarts=0) surfaces the crash instead of looping."""
    engine = _fast_engine(FaultInjector().plan("step", at=0, kind="crash"))
    engine.submit(_requests(1, seed=9)[0])
    with pytest.raises(EngineCrash):
        engine.run(max_restarts=0)


# -- quarantine: only the poisoned request fails -------------------------


def test_permanent_prefill_fault_fails_only_poisoned_request():
    """A permanent fault during one request's admission prefill fails
    exactly that request (FAILED, done set, no slot leaked); everyone
    else decodes to byte-identical streams."""
    reqs = _requests(5, seed=11)
    clean = _run_clean(reqs)

    reqs2 = _clone(reqs)
    reqs2[2].done = threading.Event()
    inj = FaultInjector().plan("prefill", at=2, kind="permanent")
    engine = _fast_engine(inj)
    for r in reqs2:
        engine.submit(r)
    faulted = engine.run()

    poisoned = reqs2[2]  # admissions are FIFO: 3rd prefill = 3rd submit
    assert poisoned.status is RequestStatus.FAILED
    assert poisoned.done.is_set()
    assert "permanent" in poisoned.error
    assert poisoned.id not in faulted
    for a, b in zip(reqs, reqs2):
        if b is not poisoned:
            np.testing.assert_array_equal(clean[a.id], faulted[b.id])
    assert engine.metrics.n_failed == 1
    assert engine.pool.n_active == 0 and engine.pool.n_free == 2


def test_step_fault_naming_request_quarantines_it():
    """A persistent transient step fault carrying a req_id quarantines
    that request instead of crashing the engine; the rest finish."""
    reqs = _requests(3, seed=13)
    clean = _run_clean(reqs)

    reqs2 = _clone(reqs)
    inj = FaultInjector().plan(
        "step", at=1, kind="transient", times=3, req_id=reqs2[0].id
    )
    engine = _fast_engine(inj, max_retries=2)
    for r in reqs2:
        engine.submit(r)
    faulted = engine.run()

    assert reqs2[0].status is RequestStatus.FAILED
    assert engine.metrics.n_failed == 1
    assert engine.metrics.n_restarts == 0
    for a, b in zip(reqs[1:], reqs2[1:]):
        np.testing.assert_array_equal(clean[a.id], faulted[b.id])


# -- chaos at multi-step horizons ----------------------------------------


@pytest.mark.parametrize("horizon", [2, 4])
def test_chaos_parity_at_multi_step_horizon(horizon):
    """Transient faults AND a full crash with a fused K-substep decode
    program: the fault boundary is the horizon dispatch, recovery
    replays the recorded tokens, and streams stay byte-identical to an
    unfaulted engine — the pipelined hot path keeps the fault-tolerance
    contract."""
    reqs = _requests(6, seed=23)
    clean = _run_clean(reqs)

    reqs2 = _clone(reqs)
    inj = (FaultInjector()
           .plan("step", at=1, kind="transient")
           .plan("step", at=3, kind="crash"))
    engine = _fast_engine(inj, decode_horizon=horizon)
    for r in reqs2:
        engine.submit(r)
    faulted = engine.run()

    _assert_parity(reqs, clean, reqs2, faulted)
    assert engine.metrics.n_retries == 1
    assert engine.metrics.n_restarts == 1
    assert all(r.status is RequestStatus.FINISHED for r in reqs2)


def test_sampled_crash_recovery_key_continuity():
    """Crash mid-decode at temperature > 0: each slot's sampling key is
    split at admission and persisted host-side, and token i is drawn
    with fold_in(slot_key, position) — so after replay (teacher-forced
    recorded tokens, keys re-seated) the resumed SAMPLED stream is
    byte-identical to an uninterrupted sampled run. This closes the
    key-stream-continuity gap stepwise replay alone could not (a shared
    per-dispatch key would have advanced differently)."""
    def build(faults=None):
        return ServingEngine(
            CFG, _params(), n_slots=3, temperature=0.8, top_k=8,
            rng_seed=21, faults=faults, retry_backoff_s=0.001,
            max_backoff_s=0.004,
        )

    reqs = _requests(6, seed=17)
    clean_eng = build()
    for r in reqs:
        clean_eng.submit(r)
    clean = clean_eng.run()

    for horizon_crash_at in (1, 3):
        reqs2 = _clone(reqs)
        inj = FaultInjector().plan("step", at=horizon_crash_at,
                                   kind="crash")
        engine = build(inj)
        for r in reqs2:
            engine.submit(r)
        faulted = engine.run()
        assert engine.metrics.n_restarts == 1
        assert all(r.status is RequestStatus.FINISHED for r in reqs2)
        _assert_parity(reqs, clean, reqs2, faulted)


def test_crash_with_unsynced_horizon_drops_no_tokens():
    """Crash while a dispatched horizon is still awaiting readback: its
    tokens were never recorded, so replay regenerates them — no
    duplicates, no gaps. The crash at dispatch #2 lands with dispatch
    #1's token block still in flight."""
    reqs = _requests(4, seed=29)
    clean = _run_clean(reqs)

    reqs2 = _clone(reqs)
    inj = FaultInjector().plan("step", at=1, kind="crash")
    engine = _fast_engine(inj, decode_horizon=4)
    for r in reqs2:
        engine.submit(r)
    faulted = engine.run()
    _assert_parity(reqs, clean, reqs2, faulted)
    assert engine.metrics.n_restarts == 1


def test_chunked_replay_recovery():
    """Forced chunked replay: recovery re-prefills prompt+tokens in one
    bucketed pass (O(len/bucket) device calls) instead of stepwise
    teacher-forcing. On this backend/model the prefill-path caches
    reproduce the decode trajectory's argmax choices, so the streams
    still match the clean run (the general guarantee is completion;
    byte-parity under forced chunked replay is what the "auto" probe
    exists to verify before relying on it)."""
    reqs = _requests(4, seed=31)
    clean = _run_clean(reqs)

    reqs2 = _clone(reqs)
    inj = FaultInjector().plan("step", at=2, kind="crash")
    engine = _fast_engine(inj, chunked_replay=True)
    for r in reqs2:
        engine.submit(r)
    faulted = engine.run()

    assert engine.last_recover_mode == "chunked"
    assert engine.metrics.n_restarts == 1
    assert all(r.status is RequestStatus.FINISHED for r in reqs2)
    _assert_parity(reqs, clean, reqs2, faulted)


def test_auto_replay_probes_and_preserves_parity():
    """Default ("auto") replay runs the one-time bitwise parity probe
    at first recovery and picks a mode; whichever it picks, the
    recovered streams are byte-identical to a clean run (stepwise by
    construction; chunked only when the probe proved it)."""
    reqs = _requests(5, seed=37)
    clean = _run_clean(reqs)

    reqs2 = _clone(reqs)
    inj = FaultInjector().plan("step", at=3, kind="crash")
    engine = _fast_engine(inj)  # chunked_replay defaults to "auto"
    for r in reqs2:
        engine.submit(r)
    faulted = engine.run()

    assert engine._chunked_ok is not None  # probe actually ran
    assert engine.last_recover_mode in ("stepwise", "chunked")
    _assert_parity(reqs, clean, reqs2, faulted)


# -- lifecycle: cancel and deadlines -------------------------------------


def test_cancel_frees_slot_within_one_step():
    r = Request(prompt=np.arange(4, dtype=np.int32), max_new=20,
                done=threading.Event())
    engine = ServingEngine(CFG, _params(), n_slots=1, temperature=0.0)
    engine.submit(r)
    engine.step()
    assert engine.pool.n_active == 1 and r.status is RequestStatus.RUNNING
    r.cancel()
    engine.step()  # the one step the contract allows
    assert engine.pool.n_active == 0
    assert r.status is RequestStatus.CANCELLED and r.done.is_set()
    assert len(engine.results[r.id]) >= len(r.prompt)  # partial stream
    assert engine.metrics.n_cancelled == 1


def test_cancel_queued_request_never_admitted():
    engine = ServingEngine(CFG, _params(), n_slots=1, temperature=0.0)
    blocker = Request(prompt=np.arange(4, dtype=np.int32), max_new=8)
    queued = Request(prompt=np.arange(5, dtype=np.int32), max_new=8,
                     done=threading.Event())
    engine.submit(blocker)
    engine.submit(queued)
    engine.step()  # blocker holds the only slot
    assert engine.cancel(queued.id)
    engine.run()
    assert queued.status is RequestStatus.CANCELLED
    assert queued.done.is_set()
    assert queued.id not in engine.results  # never admitted, no stream
    assert blocker.status is RequestStatus.FINISHED
    assert not engine.cancel("no-such-id")


def test_deadline_expiry_frees_slot_and_admits_next():
    """A running request whose deadline elapses is retired EXPIRED
    within one step and its slot is immediately reused."""
    r1 = Request(prompt=np.arange(4, dtype=np.int32), max_new=20,
                 deadline_s=30.0, done=threading.Event())
    r2 = Request(prompt=np.arange(6, dtype=np.int32), max_new=4)
    engine = ServingEngine(CFG, _params(), n_slots=1, temperature=0.0)
    engine.submit(r1)
    engine.submit(r2)
    engine.step()
    assert engine._slots[0].req is r1
    r1.arrival_time -= 100.0  # deterministically force the deadline past
    engine.step()  # sweep retires r1, admission reuses slot 0 for r2
    assert r1.status is RequestStatus.EXPIRED and r1.done.is_set()
    assert engine._slots[0] is not None and engine._slots[0].req is r2
    engine.run()
    assert r2.status is RequestStatus.FINISHED
    assert engine.metrics.n_expired == 1


def test_deadline_checked_at_admission():
    engine = ServingEngine(CFG, _params(), n_slots=1, temperature=0.0)
    r = Request(prompt=np.arange(4, dtype=np.int32), max_new=8,
                deadline_s=0.5, done=threading.Event())
    engine.submit(r)
    r.arrival_time -= 100.0
    engine.step()
    assert r.status is RequestStatus.EXPIRED and r.done.is_set()
    assert engine.pool.n_active == 0 and r.id not in engine.results


# -- satellite fixes ------------------------------------------------------


def test_run_request_trace_survives_backpressure():
    """A flooded trace against a depth-2 queue used to die on the
    Backpressure raise; now the submit retries as steps free space and
    every request completes."""
    engine = ServingEngine(
        CFG, _params(), n_slots=1, temperature=0.0,
        scheduler=RequestScheduler(max_queue_depth=2),
    )
    reqs = _requests(6, seed=17)
    trace = [(0.0, r) for r in reqs]
    results = run_request_trace(engine, trace, time_scale=0.0)
    assert set(results) == {r.id for r in reqs}
    assert all(r.status is RequestStatus.FINISHED for r in reqs)


def test_results_dict_is_bounded():
    """Sustained traffic must not grow host memory: the results dict
    evicts oldest past results_cap, and pop_result consumes."""
    engine = ServingEngine(
        CFG, _params(), n_slots=2, temperature=0.0, results_cap=3,
    )
    reqs = _requests(8, seed=19)
    for r in reqs:
        engine.submit(r)
    engine.run()
    assert len(engine.results) == 3
    assert engine.metrics.n_finished == 8  # all served, only dict bounded
    last = reqs[-1]
    assert engine.pop_result(last.id) is not None
    assert last.id not in engine.results
    assert engine.pop_result(last.id) is None


# -- server: drain, health model, timeout-cancel -------------------------


def _post(base, payload, timeout=60):
    req = urllib.request.Request(
        f"{base}/v1/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get(base, path, timeout=10):
    try:
        with urllib.request.urlopen(f"{base}{path}", timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _warm_engine(**kw):
    """Engine with the step + a len-3 prefill program pre-compiled, so
    server-path tests aren't at the mercy of first-call compile time."""
    engine = ServingEngine(CFG, _params(), n_slots=2, temperature=0.0, **kw)
    warm = Request(prompt=np.asarray([1, 5, 9], np.int32), max_new=2)
    engine.submit(warm)
    engine.run()
    engine.pop_result(warm.id)
    return engine


def test_server_drain_finishes_inflight_and_503s_new():
    engine = _warm_engine(
        faults=FaultInjector(delay_s=0.01)  # ~10ms/step: drain overlaps
    )
    srv = ServingServer(engine, port=0).start()
    host, port = srv.address
    base = f"http://{host}:{port}"
    try:
        out = {}

        def worker():
            out["resp"] = _post(base, {"prompt": [1, 5, 9], "max_new": 12})

        t = threading.Thread(target=worker)
        t.start()
        deadline = time.time() + 10
        while engine.pool.n_active == 0 and time.time() < deadline:
            time.sleep(0.005)  # wait for admission
        assert engine.pool.n_active == 1

        status, body = _get(base, "/readyz")
        assert status == 200 and body["ready"] is True

        stopper = threading.Thread(target=lambda: srv.stop(drain_s=30))
        stopper.start()
        deadline = time.time() + 10
        while not srv._draining.is_set() and time.time() < deadline:
            time.sleep(0.002)
        status, body = _post(base, {"prompt": [2, 3], "max_new": 2})
        assert status == 503 and body["error"] == "draining"
        status, body = _get(base, "/readyz")
        assert status == 503 and body["ready"] is False

        t.join(timeout=30)
        stopper.join(timeout=30)
        status, body = out["resp"]
        assert status == 200 and len(body["tokens"]) == 15  # drained, whole
    finally:
        srv.stop()


def test_server_timeout_cancels_request_and_frees_slot():
    """504 must not leave the slot decoding for a gone client: the
    handler cancels the request; the engine frees the slot within one
    step (the fault injector's delay makes the timeout deterministic)."""
    engine = _warm_engine(faults=FaultInjector(delay_s=0.05))
    srv = ServingServer(engine, port=0, request_timeout_s=0.3).start()
    host, port = srv.address
    base = f"http://{host}:{port}"
    try:
        status, body = _post(base, {"prompt": [1, 5, 9], "max_new": 25})
        assert status == 504
        deadline = time.time() + 10
        while engine.pool.n_active and time.time() < deadline:
            time.sleep(0.01)
        assert engine.pool.n_active == 0
        assert engine.metrics.n_cancelled == 1
        status, m = _get(base, "/metrics.json")
        assert m["n_cancelled"] == 1 and m["slots_active"] == 0
    finally:
        srv.stop()


def test_server_deadline_maps_to_408():
    engine = _warm_engine(faults=FaultInjector(delay_s=0.05))
    srv = ServingServer(engine, port=0).start()
    host, port = srv.address
    try:
        status, body = _post(
            f"http://{host}:{port}",
            {"prompt": [1, 5, 9], "max_new": 25, "deadline_s": 0.2},
        )
        assert status == 408 and body["status"] == "expired"
    finally:
        srv.stop()


def test_drain_deadline_preempts_stragglers():
    """stop(drain_s) with a request that cannot finish inside the
    window: at the deadline the server preempts (cancels) it instead of
    waiting it out — the handler answers 499/cancelled with the partial
    stream dropped, and shutdown converges promptly."""
    engine = _warm_engine(
        faults=FaultInjector(delay_s=0.05)  # ~50ms/step: 25 tokens >> drain
    )
    srv = ServingServer(engine, port=0).start()
    host, port = srv.address
    base = f"http://{host}:{port}"
    out = {}
    try:
        def worker():
            out["resp"] = _post(base, {"prompt": [1, 5, 9], "max_new": 25})

        t = threading.Thread(target=worker)
        t.start()
        deadline = time.time() + 10
        while engine.pool.n_active == 0 and time.time() < deadline:
            time.sleep(0.005)
        assert engine.pool.n_active == 1

        t0 = time.time()
        srv.stop(drain_s=0.3)
        # bounded shutdown: drain window + preemption grace, not the
        # ~1.5s the straggler would have needed
        assert time.time() - t0 < 5.0
        t.join(timeout=30)
        status, body = out["resp"]
        assert status == 499 and body["status"] == "cancelled"
        assert engine.metrics.n_cancelled >= 1
    finally:
        srv.stop()


def test_watchdog_flags_hung_engine():
    """An engine wedged inside a step (here: a scripted 0.5s stall per
    boundary) stops heartbeating while its thread stays alive; once the
    beat age passes hang_threshold_s with work pending, /healthz
    reports hung and flips 503 — and recovers to 200 when the engine
    comes back."""
    engine = _warm_engine(faults=FaultInjector(delay_s=0.5))
    srv = ServingServer(engine, port=0, hang_threshold_s=0.1).start()
    host, port = srv.address
    base = f"http://{host}:{port}"
    try:
        status, body = _get(base, "/healthz")
        assert status == 200 and body["hung"] is False

        out = {}

        def worker():
            out["resp"] = _post(base, {"prompt": [1, 5, 9], "max_new": 4})

        t = threading.Thread(target=worker)
        t.start()
        saw_hung = False
        deadline = time.time() + 15
        while time.time() < deadline:
            status, body = _get(base, "/healthz")
            if status == 503 and body["hung"]:
                saw_hung = True
                assert body["ok"] is False
                assert body["beat_age_s"] > srv.hang_threshold_s
                break
            time.sleep(0.01)
        assert saw_hung, "watchdog never flagged the stalled engine"
        t.join(timeout=30)
        assert out["resp"][0] == 200  # the stall was latency, not death

        deadline = time.time() + 10
        while time.time() < deadline:
            status, body = _get(base, "/healthz")
            if status == 200:
                break
            time.sleep(0.05)
        assert status == 200 and body["hung"] is False  # beat resumed
    finally:
        srv.stop()


def test_healthz_flips_on_unrecovered_engine_death():
    """Crash every step forever with a tiny restart budget: the
    supervisor gives up, fails all in-flight work (no handler blocks
    forever), and /healthz flips to 503 on the next poll."""
    inj = FaultInjector().plan("step", at=0, kind="crash", times=10**9)
    engine = _warm_engine()
    engine.faults = inj  # armed only after warmup
    srv = ServingServer(engine, port=0, max_restarts=1).start()
    host, port = srv.address
    base = f"http://{host}:{port}"
    try:
        status, body = _get(base, "/healthz")
        assert status == 200 and body["ok"] is True

        out = {}

        def worker():  # the victim that makes the engine step (and die)
            out["resp"] = _post(base, {"prompt": [1, 5, 9], "max_new": 8})

        t = threading.Thread(target=worker)
        t.start()
        deadline = time.time() + 10
        while time.time() < deadline:
            status, body = _get(base, "/healthz")
            if status == 503:
                break
            time.sleep(0.01)
        assert status == 503 and body["ok"] is False
        assert body["engine_alive"] is False
        assert "crash" in body["last_error"]
        assert body["restarts"] >= 1

        t.join(timeout=30)
        status, body = out["resp"]  # failed fast, not a 300s hang
        assert status == 500 and body["status"] == "failed"
        status, body = _post(base, {"prompt": [2], "max_new": 2})
        assert status == 503 and body["error"] == "engine dead"
    finally:
        srv.stop()
