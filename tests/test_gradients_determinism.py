"""Numeric gradient checks + determinism tests — test classes the
reference entirely lacks (SURVEY §4: 'no gradient-check tests, no
determinism/seed tests')."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.datasets import fetchers
from deeplearning4j_tpu.models import MultiLayerNetwork
from deeplearning4j_tpu.nn import conf as C
from deeplearning4j_tpu.nn import layers as L
from deeplearning4j_tpu.utils import tree_math as tm


def _numeric_grad(f, params, eps=1e-2, sample=None, seed=0):
    # central differences under float32: eps must sit where truncation
    # O(eps^2) and roundoff O(ulp/eps) are both small — ~1e-2 is the sweet
    # spot for unit-scale params/gradients
    """Central-difference gradient of scalar f over a param pytree.

    ``sample=k`` probes a random k-coordinate subset (deterministic per
    ``seed``), returning (grad_at_probed, probe_indices) — for big
    param trees a full sweep is 2 evals per coordinate and dominates
    test wall time without adding coverage."""
    flat, unravel = jax.flatten_util.ravel_pytree(params)
    flat = np.asarray(flat, np.float64)
    if sample is None or sample >= len(flat):
        idx = np.arange(len(flat))
    else:
        idx = np.random.default_rng(seed).choice(
            len(flat), sample, replace=False
        )
    g = np.zeros(len(idx))
    for j, i in enumerate(idx):
        up, down = flat.copy(), flat.copy()
        up[i] += eps
        down[i] -= eps
        g[j] = (float(f(unravel(jnp.asarray(up, jnp.float32))))
                - float(f(unravel(jnp.asarray(down, jnp.float32))))) / (2 * eps)
    if sample is None:
        return g
    # the return SHAPE is decided by the sample argument, not by
    # whether the sample happened to cover the whole tree — callers
    # tuple-unpack
    return g, idx


@pytest.mark.parametrize("activation", ["tanh", "sigmoid", "relu"])
def test_dense_output_gradcheck(activation):
    mod = L.get("output")
    cfg = C.LayerConfig(layer_type="output", n_in=3, n_out=2,
                        activation="softmax", loss="MCXENT")
    hidden_cfg = C.LayerConfig(n_in=4, n_out=3, activation=activation)
    hmod = L.get("dense")
    k = jax.random.key(0)
    hp = hmod.init(k, hidden_cfg)
    op = mod.init(jax.random.key(1), cfg)
    x = jax.random.normal(jax.random.key(2), (5, 4))
    y = jax.nn.one_hot(jnp.array([0, 1, 0, 1, 1]), 2)
    params = {"h": hp, "o": op}

    def f(p):
        hidden = hmod.activate(p["h"], hidden_cfg, x)
        return mod.supervised_score(p["o"], cfg, hidden, y)

    analytic, _ = jax.flatten_util.ravel_pytree(jax.grad(f)(params))
    numeric = _numeric_grad(f, params)
    denom = np.maximum(np.abs(numeric) + np.abs(np.asarray(analytic)), 1e-3)
    rel = np.abs(np.asarray(analytic) - numeric) / denom
    assert rel.max() < 2e-2, rel.max()


@pytest.mark.slow
def test_lstm_bptt_gradcheck():
    mod = L.get("lstm")
    v = 4
    cfg = C.LayerConfig(layer_type="lstm", n_in=v, n_out=v, activation="tanh")
    p = mod.init(jax.random.key(0), cfg)
    x = jax.nn.one_hot(jnp.array([[0, 1, 2, 3, 1]]), v)
    y = jax.nn.one_hot(jnp.array([[1, 2, 3, 1, 0]]), v)

    def f(p):
        return mod.supervised_score(p, cfg, x, y)

    analytic, _ = jax.flatten_util.ravel_pytree(jax.grad(f)(p))
    # 48 random coordinates of the 208-param tree: same bug-detection
    # power per probe, a quarter of the evals (this was the slow lane's
    # #2 test at 59s full-sweep)
    numeric, idx = _numeric_grad(f, p, sample=48)
    analytic = np.asarray(analytic)[idx]
    denom = np.maximum(np.abs(numeric) + np.abs(analytic), 1e-3)
    rel = np.abs(analytic - numeric) / denom
    assert rel.max() < 2e-2, rel.max()


def test_conv_gradcheck_small():
    mod = L.get("conv_downsample")
    cfg = C.LayerConfig(layer_type="conv_downsample", n_in=1, num_feature_maps=2,
                        filter_size=(3, 3), stride=(2, 2), activation="tanh")
    p = mod.init(jax.random.key(0), cfg)
    x = jax.random.normal(jax.random.key(1), (2, 8, 8, 1))

    def f(p):
        # conv + smooth activation; max-pool is piecewise linear and its
        # argmax flips break central differences at usable eps
        return jnp.sum(jnp.tanh(mod.conv(p, cfg, x)) ** 2)

    analytic, _ = jax.flatten_util.ravel_pytree(jax.grad(f)(p))
    numeric = _numeric_grad(f, p)
    denom = np.maximum(np.abs(numeric) + np.abs(np.asarray(analytic)), 1e-2)
    rel = np.abs(np.asarray(analytic) - numeric) / denom
    assert rel.max() < 3e-2, rel.max()


def test_training_is_deterministic_by_seed():
    ds = fetchers.iris().normalize_zero_mean_unit_variance()
    train, _ = ds.split_test_and_train(110)

    def run():
        mc = C.list_builder(
            C.LayerConfig(activation="tanh", num_iterations=30), sizes=[5],
            n_in=4, n_out=3, pretrain=False, backward=True,
        )
        net = MultiLayerNetwork(mc, seed=99)
        net.init()
        net.fit_dataset(train)
        return net.params_vector()

    assert np.array_equal(run(), run())


def test_dropconnect_masks_weights():
    mod = L.get("dense")
    cfg = C.LayerConfig(n_in=6, n_out=4, dropout=0.5, use_drop_connect=True,
                        activation="linear")
    p = mod.init(jax.random.key(0), cfg)
    x = jnp.ones((3, 6))
    eval_out = mod.activate(p, cfg, x)
    train1 = mod.activate(p, cfg, x, key=jax.random.key(1), training=True)
    train2 = mod.activate(p, cfg, x, key=jax.random.key(2), training=True)
    assert not jnp.allclose(train1, eval_out)
    assert not jnp.allclose(train1, train2)


def test_spark_style_local_sgd_iris(devices):
    """End-to-end parameter-averaged MLP on Iris over the 8-device mesh
    ≙ TestSparkMultiLayer.java:182 (local[8] param averaging)."""
    from deeplearning4j_tpu.evaluation import Evaluation
    from deeplearning4j_tpu.parallel import data_parallel_mesh, local_sgd_step

    ds = fetchers.iris().normalize_zero_mean_unit_variance()
    train, test = ds.split_test_and_train(104)  # 104 divides by 8
    mc = C.list_builder(
        C.LayerConfig(activation="tanh"), sizes=[8], n_in=4, n_out=3,
        pretrain=False, backward=True,
    )
    net = MultiLayerNetwork(mc, seed=11)
    params = net.init()

    def loss(p, x, y, key=None):
        return net.supervised_score_fn(p, x, y)

    mesh = data_parallel_mesh(8)
    step = local_sgd_step(loss, mesh, local_steps=5, lr=0.3)
    x = jnp.asarray(train.features)
    y = jnp.asarray(train.labels)
    for i in range(40):
        params, l = step(params, x, y, jax.random.key(i))
    net.params = list(params)
    ev = Evaluation(3)
    ev.eval(test.labels, np.asarray(net.output(test.features)))
    assert ev.f1() > 0.85, ev.stats()
