"""End-to-end network tests ≙ reference MultiLayerTest.java (DBN on Iris —
the de-facto acceptance test), OutputLayerTest, EvalTest."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.datasets import ListDataSetIterator
from deeplearning4j_tpu.datasets import fetchers
from deeplearning4j_tpu.evaluation import Evaluation
from deeplearning4j_tpu.models import MultiLayerNetwork
from deeplearning4j_tpu.nn import conf as C


def _mlp_config(n_in, n_out, hidden, **kw):
    base = C.LayerConfig(
        activation="tanh",
        lr=kw.pop("lr", 0.1),
        num_iterations=kw.pop("num_iterations", 100),
        optimization_algo=kw.pop(
            "optimization_algo", C.OptimizationAlgorithm.CONJUGATE_GRADIENT
        ),
        use_adagrad=True,
        momentum=0.5,
        weight_init="vi",
    )
    return C.list_builder(
        base, sizes=hidden, n_in=n_in, n_out=n_out,
        hidden_layer_type=kw.pop("hidden_layer_type", "dense"), **kw
    )


def test_evaluation_metrics_math():
    """≙ EvalTest:38 — confusion-matrix math asserts."""
    ev = Evaluation(3)
    labels = np.array([0, 0, 1, 1, 2, 2])
    preds = np.array([0, 1, 1, 1, 2, 0])
    ev.eval(labels, preds)
    assert ev.accuracy() == pytest.approx(4 / 6)
    assert ev.recall(0) == pytest.approx(0.5)
    assert ev.recall(1) == pytest.approx(1.0)
    assert ev.precision(1) == pytest.approx(2 / 3)
    assert 0 < ev.f1() <= 1
    assert "Accuracy" in ev.stats()


def test_evaluation_stats_per_class_report():
    """≙ Evaluation.stats:81 — golden per-class report on a 3-class
    imbalanced confusion matrix (VERDICT r4 #6): the text must surface
    per-class tp/fp/fn/support and precision/recall/F1, plus the
    reference's per-cell "Actual Class i was predicted..." enumeration.
    """
    ev = Evaluation(3)
    # imbalanced: class 0 dominant (8 true), class 2 rare (2 true)
    labels = np.array([0] * 8 + [1] * 4 + [2] * 2)
    preds = np.array([0, 0, 0, 0, 0, 0, 1, 2,   # 6 right, 1->1, 1->2
                      1, 1, 0, 0,               # 2 right, 2->0
                      2, 0])                    # 1 right, 1->0
    ev.eval(labels, preds)
    s = ev.stats()
    # per-cell enumeration (reference format)
    assert "Actual Class 0 was predicted with Predicted 0 with count 6 times" in s
    assert "Actual Class 1 was predicted with Predicted 0 with count 2 times" in s
    # zero cells are NOT enumerated (class 1 never predicted as 2)
    assert "Actual Class 1 was predicted with Predicted 2" not in s
    # per-class table: class 0 tp=6 fp=3 fn=2 support=8 p=6/9 r=6/8
    assert ev.false_positives(0) == 3 and ev.false_negatives(0) == 2
    row0 = next(ln for ln in s.splitlines() if ln.strip().startswith("0 "))
    assert "     0     6     3     2        8" in row0
    assert f"{6/9:.4f}" in row0 and f"{6/8:.4f}" in row0
    # class 2: tp=1 fp=1 fn=1 support=2 -> p=r=f1=0.5
    row2 = next(ln for ln in s.splitlines() if ln.strip().startswith("2 "))
    assert "0.5000" in row2
    # aggregates still present
    assert "Accuracy" in s and "F1 Score" in s


def test_mlp_backprop_iris():
    """Plain MLP, full backprop, matches/beats reference Iris quality."""
    ds = fetchers.iris().normalize_zero_mean_unit_variance()
    train, test = ds.split_test_and_train(110)
    mc = _mlp_config(4, 3, [8], num_iterations=200)
    mc.pretrain = False
    mc.backward = True
    net = MultiLayerNetwork(mc, seed=42)
    net.init()
    net.fit_dataset(train)
    ev = Evaluation(3)
    ev.eval(test.labels, np.asarray(net.output(test.features)))
    assert ev.f1() > 0.85, ev.stats()


def test_dbn_pretrain_finetune_iris():
    """DBN (RBM stack) with CD pretraining + CG finetune on Iris
    ≙ MultiLayerTest.testDbn (MultiLayerTest.java:79-116)."""
    ds = fetchers.iris().normalize_zero_mean_unit_variance()
    train, test = ds.split_test_and_train(110)
    base = C.LayerConfig(
        layer_type="rbm",
        activation="tanh",
        visible_unit=C.VisibleUnit.GAUSSIAN,
        hidden_unit=C.HiddenUnit.BINARY,
        lr=0.05,
        k=1,
        num_iterations=100,
        optimization_algo=C.OptimizationAlgorithm.CONJUGATE_GRADIENT,
    )
    mc = C.list_builder(base, sizes=[6, 4], n_in=4, n_out=3, hidden_layer_type="rbm")
    mc.backward = True
    net = MultiLayerNetwork(mc, seed=7)
    net.init()
    net.fit(ListDataSetIterator(train, 110))
    ev = Evaluation(3)
    ev.eval(test.labels, np.asarray(net.output(test.features)))
    # the reference's DBN-on-Iris asserts nothing numeric; require real learning
    assert ev.accuracy() > 0.85, ev.stats()


@pytest.mark.slow
def test_autoencoder_stack_pretrain():
    ds = fetchers.mnist(n=256).binarize()
    base = C.LayerConfig(
        layer_type="autoencoder",
        activation="sigmoid",
        corruption_level=0.3,
        lr=0.1,
        num_iterations=30,
        optimization_algo=C.OptimizationAlgorithm.GRADIENT_DESCENT,
    )
    mc = C.list_builder(base, sizes=[64], n_in=784, n_out=10, hidden_layer_type="autoencoder")
    net = MultiLayerNetwork(mc, seed=0)
    net.init()
    from deeplearning4j_tpu.datasets import ListDataSetIterator as LI

    net.pretrain(LI(ds, 128))
    recon = np.asarray(net.reconstruct(ds.features[:32], 1))
    assert recon.shape == (32, 784)
    err = float(((recon - ds.features[:32]) ** 2).mean())
    assert err < 0.25, err


def test_params_vector_roundtrip_and_merge():
    mc = _mlp_config(4, 3, [5], num_iterations=5)
    net = MultiLayerNetwork(mc, seed=1)
    net.init()
    vec = net.params_vector()
    net2 = MultiLayerNetwork(mc, seed=2)
    net2.init()
    assert not np.allclose(vec, net2.params_vector())
    net2.set_params_vector(vec)
    assert np.allclose(vec, net2.params_vector())

    # merge = parameter averaging (≙ MultiLayerNetwork.merge:1354)
    net3 = MultiLayerNetwork(mc, seed=3)
    net3.init()
    v3 = net3.params_vector()
    net3.merge([net2])
    assert np.allclose(net3.params_vector(), (v3 + vec) / 2, atol=1e-6)


def test_serde_roundtrip():
    mc = _mlp_config(4, 3, [5], num_iterations=5)
    net = MultiLayerNetwork(mc, seed=1)
    net.init()
    blob = net.to_bytes()
    net2 = MultiLayerNetwork.from_bytes(blob)
    x = np.random.default_rng(0).normal(size=(4, 4)).astype(np.float32)
    assert np.allclose(np.asarray(net.output(x)), np.asarray(net2.output(x)), atol=1e-6)


@pytest.mark.slow
def test_conv_network_lenet_style():
    """Conv+pool -> dense -> softmax on synthetic MNIST; the trainable conv
    net the reference never finished (its conv layer was forward-only)."""
    ds = fetchers.mnist(n=512)
    train, test = ds.split_test_and_train(448)
    confs = [
        C.LayerConfig(
            layer_type="conv_downsample", n_in=1, num_feature_maps=8,
            filter_size=(5, 5), stride=(2, 2), activation="relu",
        ),
        C.LayerConfig(layer_type="dense", n_in=8 * 12 * 12, n_out=64, activation="relu"),
        C.LayerConfig(
            layer_type="output", n_in=64, n_out=10, activation="softmax",
            loss="MCXENT", lr=0.05, num_iterations=100, use_adagrad=True,
            optimization_algo=C.OptimizationAlgorithm.GRADIENT_DESCENT,
        ),
    ]
    mc = C.MultiLayerConfig(confs=confs, pretrain=False, backward=True)
    net = MultiLayerNetwork(mc, seed=5)
    net.init()
    net.fit_dataset(train)
    ev = Evaluation(10)
    ev.eval(test.labels, np.asarray(net.output(test.features)))
    assert ev.accuracy() > 0.8, ev.stats()
