"""graftlint + runtime-sanitizer suite.

Two halves, mirroring ``deeplearning4j_tpu/analysis``:

- STATIC: each rule is fed synthetic sources seeded with the exact bug
  class it exists for (host sync in a hot path, the PR-2 aliasing race,
  PRNG reuse, guarded-by violations, trace-cache defeats) and must flag
  the violation AND stay quiet on the blessed idiom next to it. Plus
  baseline mechanics (stable keys, stale detection, --strict) and the
  load-bearing meta-test: the linter runs clean over this repo.
- RUNTIME: each sanitizer is fed a seeded violation (lock-order
  inversion, unlocked cross-thread write, blocking sync inside the
  dispatch critical section, in-flight buffer mutation, out-of-family
  compiled program) and must report it; the disabled path must be
  bit-identical to production (raw locks, pristine numpy functions) —
  the same zero-overhead bar ``test_obs.py`` holds the tracer to.
"""

import json
import threading

import numpy as np
import pytest

from deeplearning4j_tpu.analysis.baseline import Baseline
from deeplearning4j_tpu.analysis.core import ModuleInfo
from deeplearning4j_tpu.analysis.lint import (
    default_root,
    lint_paths,
    main as lint_main,
)
from deeplearning4j_tpu.analysis.rules import run_rules
from deeplearning4j_tpu.analysis.sanitizers import (
    CompileCountGuard,
    LockSanitizer,
    SanitizerViolation,
    SyncSanitizer,
    note_access,
    wrap_lock,
)


def _findings(src, rules=None):
    return run_rules(ModuleInfo("synthetic.py", src, "synthetic.py"),
                     rules=rules)


def _rules_fired(src, rules=None):
    return [f.rule for f in _findings(src, rules)]


# -- rule: host-sync ------------------------------------------------------


def test_host_sync_flags_hot_path_only():
    src = '''
import numpy as np

# lint: hot-path
def dispatch(x):
    return np.asarray(x)

def cold(x):
    return np.asarray(x)
'''
    fs = _findings(src, ["host-sync"])
    assert [f.qualname for f in fs] == ["dispatch"]


def test_host_sync_flags_item_float_bool():
    src = '''
# lint: hot-path
def f(x, y):
    a = x.item()
    b = float(y)
    c = bool(x)
    return a, b, c
'''
    assert _rules_fired(src, ["host-sync"]) == ["host-sync"] * 3


def test_host_sync_sync_ok_suppresses():
    src = '''
import numpy as np

# lint: hot-path
def process(toks):
    host = np.asarray(toks)  # lint: sync-ok the designated readback
    return host
'''
    assert _findings(src, ["host-sync"]) == []


# -- rule: zero-copy-alias ------------------------------------------------


def test_alias_flags_mutation_after_dispatch():
    src = '''
import numpy as np
import jax.numpy as jnp

def f(fn, seq):
    buf = np.zeros((8,), np.int32)
    fn(jnp.asarray(buf))
    buf[0] = 1
'''
    assert _rules_fired(src, ["zero-copy-alias"]) == ["zero-copy-alias"]


def test_alias_flags_buffer_hoisted_out_of_loop():
    # the engine's `pos` replay race: one buffer, dispatched and
    # mutated every iteration — iteration N's write races iteration
    # N-1's in-flight program
    src = '''
import numpy as np
import jax.numpy as jnp

def g(fn, n):
    pos = np.zeros((4,), np.int32)
    for j in range(n):
        fn(jnp.asarray(pos))
        pos[0] += 1
'''
    assert _rules_fired(src, ["zero-copy-alias"]) == ["zero-copy-alias"]


def test_alias_fresh_buffer_per_iteration_is_clean():
    # rebinding starts a new generation: every iteration dispatches a
    # buffer nothing will ever write to again (the engine's `pad`
    # prefill idiom)
    src = '''
import numpy as np
import jax.numpy as jnp

def g(fn, chunks):
    for seq in chunks:
        pad = np.zeros((1, 8), np.int32)
        pad[0, :len(seq)] = seq
        fn(jnp.asarray(pad))
'''
    assert _findings(src, ["zero-copy-alias"]) == []


def test_alias_defensive_copy_is_clean():
    src = '''
import numpy as np
import jax.numpy as jnp

def g(fn, n):
    pos = np.zeros((4,), np.int32)
    for j in range(n):
        fn(jnp.asarray(pos.copy()))
        pos[0] += 1
'''
    assert _findings(src, ["zero-copy-alias"]) == []


def test_alias_class_attribute_variant():
    src = '''
import numpy as np
import jax.numpy as jnp

class Engine:
    def seat(self, slot, kd):
        self.keys[slot] = kd

    def dispatch(self, fn):
        return fn(jnp.asarray(self.keys))

    def dispatch_safe(self, fn):
        return fn(jnp.asarray(self.keys.copy()))
'''
    fs = _findings(src, ["zero-copy-alias"])
    assert [f.qualname for f in fs] == ["Engine.dispatch"]


def test_alias_ok_suppresses():
    src = '''
import jax.numpy as jnp

def f(fn, buf):
    fn(jnp.asarray(buf))  # lint: alias-ok caller guarantees no writes
    buf[0] = 1
'''
    assert _findings(src, ["zero-copy-alias"]) == []


# -- rule: prng-reuse -----------------------------------------------------


def test_prng_flags_double_consume():
    src = '''
import jax

def f(model, x):
    k = jax.random.split(jax.random.key(0), 2)[0]
    a = model(x, k)
    b = model(x, k)
    return a, b
'''
    fs = _findings(src, ["prng-reuse"])
    assert [f.rule for f in fs] == ["prng-reuse"]


def test_prng_split_between_sinks_is_clean():
    src = '''
import jax

def f(model, x, key):
    key, k1 = jax.random.split(key)
    a = model(x, k1)
    key, k2 = jax.random.split(key)
    b = model(x, k2)
    return a, b
'''
    assert _findings(src, ["prng-reuse"]) == []


def test_prng_exclusive_branches_are_clean():
    src = '''
import jax

def f(model, x, flag):
    k = jax.random.split(jax.random.key(0), 2)[1]
    if flag:
        return model(x, k)
    else:
        return model(x * 2, k)
'''
    assert _findings(src, ["prng-reuse"]) == []


def test_prng_outer_key_consumed_in_loop_flags():
    src = '''
import jax

def f(model, xs):
    k = jax.random.split(jax.random.key(0), 2)[0]
    out = []
    for x in xs:
        out.append(model(x, k))
    return out
'''
    assert _rules_fired(src, ["prng-reuse"]) == ["prng-reuse"]


# -- rule: lock-discipline ------------------------------------------------


def test_lock_discipline_guarded_by():
    src = '''
import threading

class Pool:
    def __init__(self):
        self._lock = threading.Lock()
        self._free = []  # guarded-by: _lock

    def bad(self):
        return len(self._free)

    def good(self):
        with self._lock:
            return len(self._free)

    def helper(self):  # lint: holds _lock
        return self._free.pop()
'''
    fs = _findings(src, ["lock-discipline"])
    assert [f.qualname for f in fs] == ["Pool.bad"]


def test_lock_ok_suppresses():
    src = '''
import threading

class Pool:
    def __init__(self):
        self._lock = threading.Lock()
        self._free = []  # guarded-by: _lock

    def snapshot(self):
        return list(self._free)  # lint: lock-ok read-only debug dump
'''
    assert _findings(src, ["lock-discipline"]) == []


# -- rule: retrace-hazard -------------------------------------------------


def test_retrace_flags_immediate_invocation_and_loop_jit():
    src = '''
import jax

def serve(fns, xs):
    out = [jax.jit(fns[0])(xs[0])]
    for f in fns:
        g = jax.jit(f)
        out.append(g(xs[0]))
    return out

class E:
    def __init__(self, f):
        self._fn = jax.jit(f)
'''
    fs = _findings(src, ["retrace-hazard"])
    assert len(fs) == 2  # immediate call + jit-in-loop; __init__ exempt
    assert all(f.qualname == "serve" for f in fs)


def test_retrace_ok_suppresses():
    src = '''
import jax

def probe(f, x):
    return jax.jit(f)(x)  # lint: retrace-ok one-shot probe
'''
    assert _findings(src, ["retrace-hazard"]) == []


# -- finding keys + baseline ----------------------------------------------


def test_finding_key_is_line_number_independent():
    src = '''
import numpy as np

# lint: hot-path
def f(x):
    return np.asarray(x)
'''
    (f1,) = _findings(src, ["host-sync"])
    (f2,) = _findings("\n\n\n" + src, ["host-sync"])
    assert f1.line != f2.line
    assert f1.key == f2.key


def test_baseline_roundtrip_and_stale(tmp_path):
    src = '''
import numpy as np

# lint: hot-path
def f(x):
    return np.asarray(x)
'''
    (f1,) = _findings(src, ["host-sync"])
    path = tmp_path / ".graftlint.json"
    bl = Baseline(str(path))
    bl.write([f1])
    data = json.loads(path.read_text())
    assert data["version"] == 1
    assert data["accepted"][0]["key"] == f1.key
    assert data["accepted"][0]["reason"].startswith("TODO")

    bl2 = Baseline(str(path))
    new, suppressed, stale = bl2.split([f1])
    assert (new, len(suppressed), stale) == ([], 1, [])
    # the site disappears -> its entry goes stale
    new, suppressed, stale = bl2.split([])
    assert stale == [f1.key]


def test_lint_cli_exit_codes(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import numpy as np\n"
        "# lint: hot-path\n"
        "def f(x):\n"
        "    return np.asarray(x)\n"
    )
    bl = tmp_path / "bl.json"
    assert lint_main([str(bad), "--no-baseline"]) == 1
    assert lint_main([str(bad), "--baseline", str(bl),
                      "--write-baseline"]) == 0
    # baselined -> clean; --strict still fails on the TODO reason
    assert lint_main([str(bad), "--baseline", str(bl)]) == 0
    assert lint_main([str(bad), "--baseline", str(bl), "--strict"]) == 1
    data = json.loads(bl.read_text())
    data["accepted"][0]["reason"] = "probe path, compiled once"
    bl.write_text(json.dumps(data))
    assert lint_main([str(bad), "--baseline", str(bl), "--strict"]) == 0


def test_repo_lints_clean():
    """The load-bearing meta-test: the shipped package has no
    unaccepted findings under all five rules (CI runs the same check
    via ``python -m deeplearning4j_tpu lint --strict``)."""
    findings, errors = lint_paths([default_root()])
    assert errors == []
    assert [f.render() for f in findings] == []


# -- sanitizers: disabled path --------------------------------------------


def test_disabled_sanitizers_cost_nothing():
    """Mirror of the tracer's overhead guard: with no sanitizer
    installed, wrap_lock is the identity, numpy's functions are the
    pristine originals, and note_access is a no-op."""
    lock = threading.Lock()
    assert wrap_lock(lock, "x") is lock
    orig_asarray, orig_array = np.asarray, np.array
    note_access("anything", write=True)  # must not record or raise
    assert np.asarray is orig_asarray
    assert np.array is orig_array

    san = SyncSanitizer().install()
    try:
        assert np.asarray is not orig_asarray
    finally:
        san.uninstall()
    # uninstall restores the exact originals
    assert np.asarray is orig_asarray
    assert np.array is orig_array
    assert wrap_lock(lock, "x") is lock


# -- sanitizers: seeded violations ----------------------------------------


def test_lock_sanitizer_reports_order_inversion():
    with LockSanitizer() as san:
        a = wrap_lock(threading.Lock(), "a")
        b = wrap_lock(threading.Lock(), "b")
        with a:
            with b:
                pass
        with b:
            with a:  # closes the a->b->a cycle
                pass
    assert any("lock-order inversion" in v for v in san.violations)
    with pytest.raises(SanitizerViolation):
        san.assert_clean()


def test_lock_sanitizer_consistent_order_is_clean():
    with LockSanitizer() as san:
        a = wrap_lock(threading.Lock(), "a")
        b = wrap_lock(threading.Lock(), "b")
        for _ in range(3):
            with a:
                with b:
                    pass
    san.assert_clean()


def test_lock_sanitizer_reports_unlocked_cross_thread_write():
    with LockSanitizer() as san:
        def writer():
            note_access("shared.table", write=True)

        t = threading.Thread(target=writer, name="other-writer")
        t.start()
        t.join()
        note_access("shared.table", write=True)
    assert any("shared.table" in v for v in san.violations)


def test_lock_sanitizer_single_writer_is_clean():
    # single-writer/multi-reader under the GIL is the codebase's
    # blessed pattern (server._last_beat etc.) — not a violation
    with LockSanitizer() as san:
        for _ in range(5):
            note_access("swmr.value", write=True)
    san.assert_clean()


def test_sync_sanitizer_budget_and_phases():
    import jax

    x = jax.numpy.arange(4)
    san = SyncSanitizer(budgets={"dispatch": 0}).install()
    try:
        san.set_phase("process")
        np.asarray(x)
        np.asarray(np.arange(4))  # plain numpy: not a device sync
        san.set_phase("dispatch")
        np.asarray(x)  # over budget
    finally:
        san.uninstall()
    assert san.sync_count("process") == 1
    assert san.sync_count("dispatch") == 1
    assert any("dispatch" in v for v in san.violations)
    with pytest.raises(SanitizerViolation):
        san.assert_budgets()


def test_sync_sanitizer_alias_tripwire():
    san = SyncSanitizer()
    buf = np.arange(8, dtype=np.int32)
    san.track("dispatch.keys", buf)
    san.check("dispatch.keys")
    assert san.violations == []
    san.track("dispatch.keys", buf)
    buf[3] = 99  # mutated while "in flight"
    san.check("dispatch.keys")
    assert any("in flight" in v for v in san.violations)


# -- sanitizers: engine integration ---------------------------------------


@pytest.fixture(scope="module")
def tiny_serving():
    import jax

    from deeplearning4j_tpu.models.transformer import (
        TransformerConfig,
        init_transformer,
    )

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=1, d_ff=64,
        max_len=32,
    )
    return cfg, init_transformer(jax.random.key(0), cfg)


def _engine(cfg, params, **kw):
    from deeplearning4j_tpu.serving import ServingEngine

    kw.setdefault("n_slots", 2)
    kw.setdefault("temperature", 0.0)
    kw.setdefault("decode_horizon", 2)
    return ServingEngine(cfg, params, **kw)


def _reqs(n, seed=0):
    from deeplearning4j_tpu.serving import Request

    rng = np.random.default_rng(seed)
    return [
        Request(
            id=f"r{i}",
            prompt=rng.integers(1, 60, (int(rng.integers(3, 8)),))
            .astype(np.int32),
            max_new=int(rng.integers(3, 8)),
        )
        for i in range(n)
    ]


def test_engine_clean_under_all_sanitizers(tiny_serving):
    """A full serve run with every sanitizer armed: zero blocking
    syncs inside the dispatch critical section, exactly one readback
    per processed horizon, untouched dispatch buffers, compiled
    programs inside their contract families, no lock findings."""
    cfg, params = tiny_serving
    lock_san = LockSanitizer().install()
    sync_san = SyncSanitizer().install()
    try:
        eng = _engine(cfg, params)
        eng.attach_sanitizer(sync_san)
        for r in _reqs(4):
            eng.scheduler.submit(r)
        results = eng.run()
    finally:
        sync_san.uninstall()
        lock_san.uninstall()
    assert len(results) == 4
    lock_san.assert_clean()
    sync_san.assert_clean()
    sync_san.assert_budgets()
    assert sync_san.sync_count("dispatch") == 0
    assert sync_san.sync_count("process") >= 1
    CompileCountGuard(eng).assert_ok()
    assert lock_san.n_wrapped > 0  # the stack's locks went through wrap_lock


def test_engine_seeded_alias_mutation_is_caught(tiny_serving):
    """Simulate the PR-2 race the defensive .copy() prevents: mutate
    the host buffer the in-flight step program is (conceptually)
    reading; the readback integrity check must fire."""
    cfg, params = tiny_serving
    sync_san = SyncSanitizer().install()
    try:
        eng = _engine(cfg, params)
        eng.attach_sanitizer(sync_san)
        eng.scheduler.submit(_reqs(1, seed=3)[0])
        eng.step()  # admit + dispatch: tracks the key snapshot
        tracked = sync_san._tracked.get("dispatch.slot_keys")
        assert tracked  # one outstanding dispatch
        buf, _snap = tracked[0]
        buf[...] += 1  # concurrent writer corrupts the in-flight buffer
        eng.step()  # processes the previous horizon -> check() fires
    finally:
        sync_san.uninstall()
    assert any("in flight" in v for v in sync_san.violations)


def test_compile_count_guard_flags_out_of_family_program(tiny_serving):
    cfg, params = tiny_serving
    eng = _engine(cfg, params)
    eng.scheduler.submit(_reqs(1)[0])
    eng.run()
    CompileCountGuard(eng).assert_ok()
    eng._step_fns[7] = object()  # a request-shaped key: retrace bug
    with pytest.raises(SanitizerViolation):
        CompileCountGuard(eng).assert_ok()
    del eng._step_fns[7]
    eng._prefill_fns[13] = object()  # off the pow2 bucket grid
    with pytest.raises(SanitizerViolation):
        CompileCountGuard(eng).assert_ok()


# -- regression: the real findings this suite was built from ---------------


def test_scheduler_len_is_locked_and_consistent():
    """__len__ now snapshots under the scheduler lock (it used to read
    the deques bare while HTTP threads appended); submit still works
    while holding the lock internally (re-entrancy regression)."""
    from deeplearning4j_tpu.serving import RequestScheduler

    s = RequestScheduler(max_queue_depth=64)
    for r in _reqs(8, seed=1):
        s.submit(r)
    assert len(s) == 8
    # concurrent submit/len/pop must neither deadlock nor miscount
    errs = []

    def hammer(seed):
        try:
            for r in _reqs(16, seed=seed):
                s.submit(r)
                len(s)
        except Exception as e:  # pragma: no cover
            errs.append(e)

    ts = [threading.Thread(target=hammer, args=(i,)) for i in (2, 3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert errs == []
    assert len(s) == 8 + 32


def test_registry_scrape_survives_concurrent_labelset_inserts():
    """Regression for the scrape race: render() used to iterate the
    label-set dicts unlocked while first-time label sets inserted from
    other threads ("dict changed size during iteration")."""
    from deeplearning4j_tpu.obs.registry import MetricsRegistry

    reg = MetricsRegistry()
    c = reg.counter("hits_total", labelnames=("k",))
    h = reg.histogram("lat_seconds", buckets=(0.1, 1.0),
                      labelnames=("k",))
    stop = threading.Event()
    errs = []

    def scraper():
        try:
            while not stop.is_set():
                reg.render()
        except Exception as e:  # pragma: no cover
            errs.append(e)

    t = threading.Thread(target=scraper, name="metrics-serve")
    t.start()
    try:
        for i in range(300):
            c.inc(k=str(i))
            h.observe(0.05, k=str(i))
    finally:
        stop.set()
        t.join()
    assert errs == []
    assert "hits_total" in reg.render()


def test_router_health_flips_are_locked():
    """Regression: _mark_unhealthy/_poll_one used to flip
    replica.healthy without the route lock while _pick read it. The
    flip is idempotent and replica_states snapshots consistently."""
    from deeplearning4j_tpu.serving.router import ReplicaRouter

    router = ReplicaRouter([("127.0.0.1", 1), ("127.0.0.1", 2)])
    try:
        r0 = router.replicas[0]
        router._mark_unhealthy(r0, "seeded")
        router._mark_unhealthy(r0, "seeded again")  # no double-flip
        states = router.replica_states()
        assert states[r0.name]["healthy"] is False
        payload = router.health_payload()
        assert payload["replicas"][r0.name] is False
        assert payload["ok"] is True  # the other replica still routes
    finally:
        router._httpd.server_close()


def test_router_locks_are_sanitizer_clean_under_mark_unhealthy():
    """The router's health flip path under the LockSanitizer: takes
    _route_lock then the metric instrument lock, same order as _pick —
    no inversion, no unlocked write."""
    from deeplearning4j_tpu.serving.router import ReplicaRouter

    with LockSanitizer() as san:
        router = ReplicaRouter([("127.0.0.1", 1)])
        try:
            threading.Thread(
                target=router._mark_unhealthy,
                args=(router.replicas[0], "from poller"),
                name="health-poll",
            ).start()
            router.poll_health()
            router.replica_states()
        finally:
            router._httpd.server_close()
    san.assert_clean()
