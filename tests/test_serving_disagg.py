"""Disaggregated prefill/decode serving: the KV-segment wire format,
engine-level seat-path parity, the fleet controller, and
rolling-restart drain.

The tentpole contract pinned here: a generate request whose prompt
prefills on one replica and decodes on another — the KV segment
travelling as a versioned binary frame over HTTP — produces a token
stream BYTE-IDENTICAL to a monolithic replica's, greedy and sampled
alike, including across a decode-replica crash. Everything about the
transfer path is soft: any rejection (truncated frame, config-hash
mismatch, cache decline) falls back to local prefill, which is the
same bytes anyway.
"""

import json
import threading
import time
from http.server import ThreadingHTTPServer

import numpy as np
import pytest

import jax

from deeplearning4j_tpu.models.transformer import (
    TransformerConfig,
    init_transformer,
)
from deeplearning4j_tpu.obs import Tracer, merge_traces
from deeplearning4j_tpu.serving import (
    FaultInjector,
    FleetController,
    KVExportRequest,
    KVIngestRequest,
    Request,
    RequestStatus,
    RoleBalancer,
    ServingEngine,
    ServingServer,
    WireError,
    decode_segment,
    encode_segment,
)
from deeplearning4j_tpu.serving.disagg import (
    WIRE_MAGIC,
    blocks_to_slab,
    slab_to_blocks,
)
from deeplearning4j_tpu.serving.router import ReplicaRouter
from deeplearning4j_tpu.utils.httpjson import QuietHandler, send_json

CFG = TransformerConfig(
    vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64, max_len=32
)
_PARAMS = {}


def _params(seed=0):
    if seed not in _PARAMS:
        _PARAMS[seed] = init_transformer(jax.random.key(seed), CFG)
    return _PARAMS[seed]


def _drain_one(engine, req, max_steps=500):
    engine.submit(req)
    for _ in range(max_steps):
        engine.step()
        if req.done.is_set():
            return req
    raise AssertionError(f"request {req.id} never finished")


def _export_frame(engine, prompt):
    """Run a KVExportRequest through ``engine`` and frame the result."""
    req = _drain_one(engine, KVExportRequest(
        prompt=np.asarray(prompt, np.int32), done=threading.Event()))
    assert req.status == RequestStatus.FINISHED, req.error
    res = req.result
    return encode_segment(
        config_hash=res["config_hash"], tokens=res["tokens"],
        leaves=res["leaves"], logits=res["logits"],
        layout=res["layout"], block_size=res["block_size"],
    )


def _ingest(engine, frame):
    seg = decode_segment(frame, expect_hash=engine.config_hash)
    req = _drain_one(engine, KVIngestRequest(
        segment=seg, done=threading.Event()))
    assert req.status == RequestStatus.FINISHED
    return req.result


# -- wire format ----------------------------------------------------------


def _slab_leaves(dtype, seed=0):
    """Two (L, C, 1, Tpad, H) leaves in the given dtype."""
    rng = np.random.default_rng(seed)
    raw = [rng.standard_normal((2, 2, 1, 16, 8)) for _ in range(2)]
    return [a.astype(dtype) for a in raw]


def _roundtrip(leaves, logits, **kw):
    frame = encode_segment(
        config_hash="h" * 64, tokens=[3, 5, 7], leaves=leaves,
        logits=logits, **kw)
    return frame, decode_segment(frame)


def test_wire_roundtrip_bf16_exact():
    import ml_dtypes

    leaves = _slab_leaves(ml_dtypes.bfloat16)
    logits = np.random.default_rng(1).standard_normal(
        (1, CFG.vocab_size)).astype(np.float32)
    frame, dec = _roundtrip(leaves, logits)
    assert dec["config_hash"] == "h" * 64
    assert dec["layout"] == "slab" and dec["block_size"] == 0
    np.testing.assert_array_equal(dec["tokens"],
                                  np.asarray([3, 5, 7], np.int32))
    assert dec["tokens"].dtype == np.int32
    assert dec["nbytes"] == len(frame)
    for a, b in zip(leaves, dec["leaves"]):
        assert b.dtype == a.dtype and b.shape == a.shape
        assert b.tobytes() == a.tobytes()  # bitwise, not approx
    assert dec["logits"].tobytes() == logits.tobytes()


def test_wire_roundtrip_int8_with_scale_planes():
    """int8 segments ship their f32 scale planes as ordinary extra
    leaves — mixed dtypes in one frame round-trip bitwise."""
    rng = np.random.default_rng(2)
    q = rng.integers(-128, 128, (2, 2, 1, 16, 8)).astype(np.int8)
    scales = rng.standard_normal((2, 2, 1, 16, 1)).astype(np.float32)
    logits = rng.standard_normal((1, CFG.vocab_size)).astype(np.float32)
    _, dec = _roundtrip([q, scales], logits)
    assert dec["leaves"][0].dtype == np.int8
    assert dec["leaves"][1].dtype == np.float32
    assert dec["leaves"][0].tobytes() == q.tobytes()
    assert dec["leaves"][1].tobytes() == scales.tobytes()


def test_wire_paged_blocklist_layout_roundtrip():
    """Paged frames carry block-list leaves; the receiver reassembles
    the batch-1 slab. slab->blocks->slab is the identity."""
    leaves = _slab_leaves(np.float32, seed=3)
    blocks = slab_to_blocks(leaves, block_size=4)
    assert blocks[0].shape == (2, 2, 4, 4, 8)
    back = blocks_to_slab(blocks)
    for a, b in zip(leaves, back):
        assert b.shape == a.shape and b.tobytes() == a.tobytes()

    logits = np.zeros((1, CFG.vocab_size), np.float32)
    _, dec = _roundtrip(blocks, logits, layout="paged", block_size=4)
    assert dec["layout"] == "paged" and dec["block_size"] == 4
    for a, b in zip(leaves, dec["leaves"]):  # slab form comes back
        assert b.shape == a.shape and b.tobytes() == a.tobytes()

    with pytest.raises(WireError):  # 16 rows don't split into 5-blocks
        slab_to_blocks(leaves, block_size=5)
    with pytest.raises(WireError):
        encode_segment(config_hash="h", tokens=[1], leaves=blocks,
                       logits=logits, layout="paged", block_size=0)


def test_wire_rejects_truncated_and_trailing():
    frame, _ = _roundtrip(_slab_leaves(np.float32),
                          np.zeros((1, 64), np.float32))
    for cut in (3, len(frame) // 2, len(frame) - 1):
        with pytest.raises(WireError) as ei:
            decode_segment(frame[:cut])
        assert ei.value.status == 400
    with pytest.raises(WireError, match="trailing"):
        decode_segment(frame + b"\x00")


def test_wire_rejects_bad_magic_version_and_header():
    frame, _ = _roundtrip(_slab_leaves(np.float32),
                          np.zeros((1, 64), np.float32))
    assert frame[:4] == WIRE_MAGIC
    with pytest.raises(WireError, match="magic"):
        decode_segment(b"XXXX" + frame[4:])
    with pytest.raises(WireError, match="version"):
        decode_segment(frame[:4] + b"\xff\x00" + frame[6:])
    # garbage where the JSON header should be
    with pytest.raises(WireError):
        decode_segment(frame[:10] + b"\xff" * (len(frame) - 10))


def test_wire_config_hash_mismatch_is_409():
    frame, _ = _roundtrip(_slab_leaves(np.float32),
                          np.zeros((1, 64), np.float32))
    with pytest.raises(WireError) as ei:
        decode_segment(frame, expect_hash="x" * 64)
    assert ei.value.status == 409
    # no expectation -> parses fine (the engine re-checks at seat time)
    assert decode_segment(frame)["config_hash"] == "h" * 64


# -- role balancer (pure policy) ------------------------------------------


def _samples(pf_q, dc_q, dc_burn=0.0):
    return {
        "p0": {"role": "prefill", "queue_depth": pf_q, "slo_burn": 0.0},
        "p1": {"role": "prefill", "queue_depth": pf_q, "slo_burn": 0.0},
        "d0": {"role": "decode", "queue_depth": dc_q,
               "slo_burn": dc_burn},
    }


def test_balancer_needs_consecutive_windows_and_dwell():
    b = RoleBalancer(threshold=2.0, windows=3, dwell_s=10.0)
    # two imbalanced samples: streak not reached, no move
    assert b.observe(0.0, _samples(0, 8)) == []
    assert b.observe(1.0, _samples(0, 8)) == []
    # a calm sample resets the streak entirely
    assert b.observe(2.0, _samples(4, 4)) == []
    assert b.observe(3.0, _samples(0, 8)) == []
    assert b.observe(4.0, _samples(0, 8)) == []
    moves = b.observe(5.0, _samples(0, 8))
    assert moves == [("p0", "decode")] or moves == [("p1", "decode")]
    # the imbalance persists but the dwell window holds moves back
    for t in (6.0, 7.0, 8.0):
        assert b.observe(t, _samples(0, 8)) == []
    # ... and releases once dwell_s has elapsed since the last move
    assert b.observe(16.0, _samples(0, 8)) != []


def test_balancer_never_empties_a_role():
    b = RoleBalancer(threshold=2.0, windows=1, dwell_s=0.0)
    one_each = {
        "p0": {"role": "prefill", "queue_depth": 9, "slo_burn": 0.0},
        "d0": {"role": "decode", "queue_depth": 0, "slo_burn": 0.0},
    }
    # prefill overloaded, but the decode pool has a single member:
    # donating it would empty the role
    for t in range(5):
        assert b.observe(float(t), one_each) == []


def test_balancer_slo_burn_counts_as_decode_pressure():
    b = RoleBalancer(threshold=2.0, windows=1, dwell_s=0.0,
                     slo_weight=4.0)
    # queues balanced, but decode tenants burn 3x their TPOT budget
    moves = b.observe(0.0, _samples(1, 1, dc_burn=3.0))
    assert moves and moves[0][1] == "decode"
    # burn <= 1.0 (objective met) adds nothing
    b2 = RoleBalancer(threshold=2.0, windows=1, dwell_s=0.0)
    assert b2.observe(0.0, _samples(1, 1, dc_burn=0.9)) == []


def test_balancer_ignores_monolithic_and_missing_pools():
    b = RoleBalancer(windows=1, dwell_s=0.0)
    mono = {
        "m0": {"role": "monolithic", "queue_depth": 50, "slo_burn": 9.0},
        "m1": {"role": "monolithic", "queue_depth": 0, "slo_burn": 0.0},
    }
    assert b.observe(0.0, mono) == []  # no pools at all
    no_decode = {
        "p0": {"role": "prefill", "queue_depth": 50, "slo_burn": 0.0},
        "p1": {"role": "prefill", "queue_depth": 50, "slo_burn": 0.0},
    }
    assert b.observe(1.0, no_decode) == []


# -- engine-level disagg parity -------------------------------------------


def _gen(engine, prompt, max_new=5):
    req = _drain_one(engine, Request(
        prompt=np.asarray(prompt, np.int32), max_new=max_new,
        done=threading.Event()))
    assert req.status == RequestStatus.FINISHED, req.error
    return engine.pop_result(req.id)


@pytest.mark.parametrize("temperature", [0.0, 0.8],
                         ids=["greedy", "sampled"])
def test_engine_disagg_parity(temperature):
    """Prefill on engine A, ship the frame, seat on engine B, decode:
    byte-identical to a monolithic engine that never saw the wire —
    and the seated generate dispatches ZERO prefill programs (the
    full-hit admission is a pure copy)."""
    prompt = list(np.random.default_rng(7).integers(1, 60, 16))
    kw = dict(n_slots=2, temperature=temperature, decode_horizon=2,
              rng_seed=5)
    pf_eng = ServingEngine(CFG, _params(), **kw)
    dc_eng = ServingEngine(CFG, _params(), prefix_cache=True, **kw)
    mono = ServingEngine(CFG, _params(), **kw)

    frame = _export_frame(pf_eng, prompt)
    res = _ingest(dc_eng, frame)
    assert res["stored"], res["reason"]
    assert dc_eng.prefill_dispatches == 0

    out_disagg = _gen(dc_eng, prompt)
    assert dc_eng.prefill_dispatches == 0  # full hit, pure copy
    out_mono = _gen(mono, prompt)
    np.testing.assert_array_equal(out_disagg, out_mono)


def test_engine_ingest_declines_are_soft():
    """Every decline reports stored=False + a reason and the engine
    keeps serving; a hash-foreign segment is refused at seat time even
    if the HTTP layer forgot to check."""
    eng = ServingEngine(CFG, _params(), n_slots=2, temperature=0.0,
                        decode_horizon=2, prefix_cache=True)
    pf = ServingEngine(CFG, _params(), n_slots=2, temperature=0.0,
                       decode_horizon=2)
    prompt = list(np.random.default_rng(9).integers(1, 60, 16))
    frame = _export_frame(pf, prompt)
    seg = decode_segment(frame)
    seg["config_hash"] = "not-this-model"
    req = _drain_one(eng, KVIngestRequest(segment=seg,
                                          done=threading.Event()))
    assert req.result["stored"] is False
    assert "hash" in req.result["reason"]

    # an engine without a prefix cache declines too (no seat exists)
    bare = ServingEngine(CFG, _params(), n_slots=2, temperature=0.0,
                         decode_horizon=2)
    res = _ingest(bare, frame)
    assert res["stored"] is False and "prefix cache" in res["reason"]

    # ... and generation still works fine after declines
    out = _gen(eng, prompt)
    np.testing.assert_array_equal(out, _gen(pf, prompt))


@pytest.mark.chaos
def test_engine_disagg_parity_across_decode_crash():
    """The decode replica crashes mid-decode AFTER seating a wire
    segment; supervised recovery replays and the stream still matches
    the monolithic reference byte for byte."""
    prompt = list(np.random.default_rng(11).integers(1, 60, 16))
    kw = dict(n_slots=2, temperature=0.8, decode_horizon=2, rng_seed=3,
              retry_backoff_s=0.001, max_backoff_s=0.004)
    pf_eng = ServingEngine(CFG, _params(), **kw)
    dc_eng = ServingEngine(
        CFG, _params(), prefix_cache=True,
        faults=FaultInjector().plan("step", at=1, kind="crash"), **kw)
    mono = ServingEngine(CFG, _params(), **kw)

    res = _ingest(dc_eng, _export_frame(pf_eng, prompt))
    assert res["stored"], res["reason"]
    req = Request(prompt=np.asarray(prompt, np.int32), max_new=6,
                  done=threading.Event())
    dc_eng.submit(req)
    dc_eng.run()  # supervised loop: crash -> recover -> finish
    assert dc_eng.metrics.n_restarts == 1
    assert req.status == RequestStatus.FINISHED, req.error
    np.testing.assert_array_equal(
        dc_eng.pop_result(req.id), _gen(mono, prompt, max_new=6))


# -- live fleet over HTTP -------------------------------------------------


def _post(addr, path, body, headers=None, timeout=60):
    import http.client

    conn = http.client.HTTPConnection(*addr, timeout=timeout)
    try:
        h = {"Content-Type": "application/json"}
        h.update(headers or {})
        conn.request("POST", path, body=json.dumps(body).encode(),
                     headers=h)
        r = conn.getresponse()
        return r.status, json.loads(r.read()), r.getheader("X-Served-By")
    finally:
        conn.close()


def _get(addr, path, timeout=10):
    import http.client

    conn = http.client.HTTPConnection(*addr, timeout=timeout)
    try:
        conn.request("GET", path)
        r = conn.getresponse()
        return r.status, r.read()
    finally:
        conn.close()


def _prom_value(text: str, series: str) -> float:
    """Value of one Prometheus sample line (series incl. labels)."""
    for line in text.splitlines():
        if line.startswith(series + " "):
            return float(line.split()[-1])
    raise AssertionError(f"{series} not found in exposition")


def test_fleet_disagg_transfer_parity_stickiness_and_trace():
    """Controller + 1 prefill + 1 decode replica, live over HTTP. A
    long prompt takes the transfer path (prefill computes KV, pushes
    the frame replica-to-replica, decode full-hits) and the output is
    byte-identical to a monolithic server's. A session follow-up
    sticks to the decode replica, a short prompt skips the transfer,
    and the merged trace chains controller dispatch -> export prefill
    -> transfer -> kv_ingest under one trace id."""
    kw = dict(n_slots=2, temperature=0.0, decode_horizon=2,
              retry_backoff_s=0.001, max_backoff_s=0.004)
    tr_pf = Tracer(process_name="serve-prefill")
    tr_dc = Tracer(process_name="serve-decode")
    pf_eng = ServingEngine(CFG, _params(), tracer=tr_pf, **kw)
    dc_eng = ServingEngine(CFG, _params(), prefix_cache=True,
                           tracer=tr_dc, **kw)
    mono_eng = ServingEngine(CFG, _params(), **kw)
    pf_srv = ServingServer(pf_eng, port=0).start()
    dc_srv = ServingServer(dc_eng, port=0).start()
    mono_srv = ServingServer(mono_eng, port=0).start()
    tr_ctl = Tracer(process_name="controller")
    ctl = FleetController(
        [pf_srv.address + ("prefill",), dc_srv.address + ("decode",)],
        disagg_threshold=12, affinity_min_match=4,
        health_interval_s=0.1, tracer=tr_ctl,
    ).start()
    try:
        prompt = [int(t) for t in
                  np.random.default_rng(13).integers(1, 60, 16)]
        status, body, served_by = _post(
            ctl.address, "/v1/generate",
            {"prompt": prompt, "max_new": 4, "session": "conv-1"})
        assert status == 200, body
        assert served_by == dc_srv.name  # decode role got the generate
        assert dc_eng.prefill_dispatches == 0  # seated -> full hit
        # per-request timing rides the relayed response: the bench's
        # end-to-end TTFT (wall - decode_s) depends on these fields
        assert body["timing"]["ttft_s"] >= 0.0
        assert body["timing"]["decode_s"] >= 0.0
        status, ref, _ = _post(mono_srv.address, "/v1/generate",
                               {"prompt": prompt, "max_new": 4})
        assert status == 200 and body["tokens"] == ref["tokens"]

        # session follow-up: sticky to the decode replica, short
        # prompt (< threshold) so no second transfer
        status, _, served_by2 = _post(
            ctl.address, "/v1/generate",
            {"prompt": prompt[:8], "max_new": 2, "session": "conv-1"})
        assert status == 200 and served_by2 == dc_srv.name

        prom = ctl.registry.render()
        assert _prom_value(prom, "fleet_disagg_total") == 1
        assert _prom_value(prom, "fleet_sticky_total") == 1
        assert _prom_value(prom, "fleet_transfer_fallback_total") == 0
        # replica-side wire metrics: one export+transfer on the
        # prefill replica, one stored ingest on the decode replica
        _, ptext = _get(pf_srv.address, "/metrics")
        ptext = ptext.decode()
        assert _prom_value(ptext, "serve_kv_exports_total") == 1
        assert _prom_value(
            ptext, 'serve_transfers_total{result="ok"}') == 1
        assert _prom_value(ptext, "serve_transfer_bytes_total") > 0
        _, dtext = _get(dc_srv.address, "/metrics")
        assert _prom_value(
            dtext.decode(),
            'serve_kv_ingests_total{result="stored"}') == 1
    finally:
        ctl.stop()
        for s in (pf_srv, dc_srv, mono_srv):
            s.stop()

    merged = merge_traces([tr_ctl.chrome_trace(), tr_pf.chrome_trace(),
                           tr_dc.chrome_trace()])
    evs = [e for e in merged["traceEvents"] if e.get("ph") == "X"]
    by_span = {e["args"]["span_id"]: e for e in evs
               if "span_id" in e.get("args", {})}
    transfer = next(e for e in evs if e["name"] == "transfer")
    export = by_span[transfer["args"]["parent_span_id"]]
    assert export["name"] == "prefill"
    assert export["args"]["prefix"] == "export"
    ingest = next(e for e in evs if e["name"] == "kv_ingest")
    assert ingest["args"]["parent_span_id"] == transfer["args"]["span_id"]
    # one trace id end to end, rooted at a controller dispatch
    tid = transfer["args"]["trace_id"]
    assert ingest["args"]["trace_id"] == tid
    assert export["args"]["trace_id"] == tid
    dispatch = by_span[export["args"]["parent_span_id"]]
    assert dispatch["name"] == "dispatch"
    assert dispatch["args"]["leg"] == "prefill"


def test_drain_undrain_rolls_through_a_two_replica_fleet():
    """POST /fleet/drain flips the replica's /readyz, the controller
    stops dispatching to it (traffic all lands on the survivor), and
    /fleet/undrain restores the rotation — the rolling-restart
    primitive, live over HTTP."""
    kw = dict(n_slots=2, temperature=0.0, decode_horizon=2,
              retry_backoff_s=0.001, max_backoff_s=0.004)
    servers = [ServingServer(ServingEngine(CFG, _params(), **kw),
                             port=0).start() for _ in range(2)]
    ctl = FleetController(
        [s.address for s in servers],  # monolithic x2
        health_interval_s=10.0,  # tests poll synchronously
        rebalance_enabled=False,
    ).start()
    try:
        victim, survivor = servers
        status, body, _ = _post(ctl.address, "/fleet/drain",
                                {"replica": victim.name})
        assert status == 200 and body["draining"] is True
        assert body["replica_response"]["in_flight"] == 0
        code, _ = _get(victim.address, "/readyz")
        assert code == 503  # drained replica reports not-ready
        code, _ = _get(survivor.address, "/readyz")
        assert code == 200

        for i in range(3):
            status, _, served_by = _post(
                ctl.address, "/v1/generate",
                {"prompt": [3, 5, 7, 11 + i], "max_new": 2})
            assert status == 200
            assert served_by == survivor.name  # never the draining one

        status, body, _ = _post(ctl.address, "/fleet/undrain",
                                {"replica": victim.name})
        assert status == 200 and body["draining"] is False
        code, _ = _get(victim.address, "/readyz")
        assert code == 200
        ctl.poll_health()
        st = ctl.fleet_state()["replicas"]
        assert st[victim.name]["draining"] is False
        assert st[victim.name]["healthy"] is True
        # the restored replica serves again when addressed directly
        status, _, _ = _post(victim.address, "/v1/generate",
                             {"prompt": [2, 4, 6, 8], "max_new": 2})
        assert status == 200
    finally:
        ctl.stop()
        for s in servers:
            s.stop()


def test_server_drain_rejects_new_work_but_keeps_engine_alive():
    eng = ServingEngine(CFG, _params(), n_slots=2, temperature=0.0,
                        decode_horizon=2)
    srv = ServingServer(eng, port=0).start()
    try:
        status, _, _ = _post(srv.address, "/drain", {})
        assert status == 200
        status, body, _ = _post(srv.address, "/v1/generate",
                                {"prompt": [1, 2, 3], "max_new": 2})
        assert status == 503, body
        # idempotent; /undrain resumes the exact same server
        _post(srv.address, "/drain", {})
        status, _, _ = _post(srv.address, "/undrain", {})
        assert status == 200
        status, body, _ = _post(srv.address, "/v1/generate",
                                {"prompt": [1, 2, 3], "max_new": 2})
        assert status == 200, body
    finally:
        srv.stop()


# -- router re-verifies model identity on replica return ------------------


class _FakeReplica:
    """A bare /healthz endpoint whose payload the test scripts — the
    'replica restarted with a different checkpoint' scenario without
    paying for a second engine."""

    def __init__(self):
        fake = self

        class Handler(QuietHandler):
            def do_GET(self):
                if fake.down:
                    self.close_connection = True
                    return
                send_json(self, 200, {
                    "ok": True, "draining": fake.draining,
                    "config_hash": fake.config_hash, "queue_depth": 0,
                })

        self.down = False
        self.draining = False
        self.config_hash = "a" * 64
        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    @property
    def address(self):
        return self._httpd.server_address[:2]

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()


def test_router_marks_restarted_replica_incompatible():
    fake = _FakeReplica()
    router = ReplicaRouter([fake.address], health_interval_s=10.0)
    name = "%s:%d" % fake.address
    try:
        router.poll_health()  # first contact pins the config hash
        st = router.replica_states()[name]
        assert st["healthy"] and not st["incompatible"]
        assert st["config_hash"] == "a" * 64

        fake.down = True  # "restart": goes dark ...
        router.poll_health()
        assert not router.replica_states()[name]["healthy"]

        fake.down = False  # ... and returns with a DIFFERENT checkpoint
        fake.config_hash = "b" * 64
        router.poll_health()
        st = router.replica_states()[name]
        assert st["incompatible"], st
        assert st["config_hash"] == "a" * 64  # the pinned identity
        # permanently out of rotation, not silently rejoined: the
        # fake's /healthz says ok but routing refuses the replica
        status, payload, served = router.route(
            {"prompt": [1, 2, 3], "max_new": 1})
        assert status == 503 and served is None
    finally:
        router._httpd.server_close()  # never start()ed
        fake.stop()


def test_router_respects_replica_draining_flag():
    fake = _FakeReplica()
    router = ReplicaRouter([fake.address], health_interval_s=10.0)
    name = "%s:%d" % fake.address
    try:
        fake.draining = True
        router.poll_health()
        st = router.replica_states()[name]
        assert st["healthy"] and st["draining"]
        fake.draining = False
        router.poll_health()
        assert not router.replica_states()[name]["draining"]
    finally:
        router._httpd.server_close()  # never start()ed
        fake.stop()


def test_controller_rejects_bad_specs_and_unknown_fleet_posts():
    with pytest.raises(ValueError):
        FleetController([])
    with pytest.raises(ValueError):
        FleetController(["localhost:notaport"])
    with pytest.raises(ValueError):
        FleetController(["localhost:8000=chef"])
    ctl = FleetController(["127.0.0.1:1=prefill",
                           "127.0.0.1:2=decode"]).start()
    try:
        status, body, _ = _post(ctl.address, "/fleet/drain",
                                {"replica": "nobody:9"})
        assert status == 404
        status, body, _ = _post(ctl.address, "/fleet/role",
                                {"replica": "127.0.0.1:1",
                                 "role": "chef"})
        assert status == 400
        status, body, _ = _post(
            ctl.address, "/fleet/role",
            {"replica": "127.0.0.1:1", "role": "decode"})
        assert status == 200
        assert ctl.fleet_state()["replicas"]["127.0.0.1:1"]["role"] == \
            "decode"
    finally:
        ctl.stop()
