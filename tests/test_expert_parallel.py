"""Expert parallelism (MoE) tests on the 8-virtual-device mesh.

The reference has no EP (SURVEY §2 P7 — absent); these validate the
beyond-parity GShard-style top-k routed MoE with all-to-all dispatch.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.parallel import mesh as mesh_lib
from deeplearning4j_tpu.parallel.expert_parallel import (
    init_moe_params,
    moe_apply,
    moe_reference,
    place_moe_params,
)

D, H = 8, 16


@pytest.fixture(scope="module")
def mesh(devices):
    return mesh_lib.expert_mesh(8)


def _tokens(n, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.normal(size=(n, D)).astype(np.float32))


def test_moe_matches_dense_reference_with_ample_capacity(mesh):
    params = init_moe_params(jax.random.key(0), D, H, 8)
    x = _tokens(64)
    # capacity_factor high enough that no token drops -> exact parity with
    # the per-token dense top-2 reference
    fn = moe_apply(mesh, k=2, capacity_factor=8.0)
    y, aux = fn(place_moe_params(mesh, params), x)
    y_ref = moe_reference(params, x, k=2)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), atol=1e-5)
    assert np.isfinite(float(aux))
    # balanced-ish routing on random data: aux stays near its floor of 1.0
    assert 0.5 < float(aux) < 4.0


def test_moe_top1_switch_routing(mesh):
    params = init_moe_params(jax.random.key(1), D, H, 8)
    x = _tokens(64, seed=1)
    fn = moe_apply(mesh, k=1, capacity_factor=8.0)
    y, _ = fn(place_moe_params(mesh, params), x)
    y_ref = moe_reference(params, x, k=1)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), atol=1e-5)


def test_moe_capacity_overflow_drops_not_corrupts(mesh):
    params = init_moe_params(jax.random.key(2), D, H, 8)
    x = _tokens(64, seed=2)
    # tiny capacity forces drops; output must stay finite and dropped
    # tokens contribute zero rather than garbage
    fn = moe_apply(mesh, k=2, capacity_factor=0.25)
    y, aux = fn(place_moe_params(mesh, params), x)
    assert np.isfinite(np.asarray(y)).all()
    assert np.isfinite(float(aux))
    # with drops, the output can't exceed the no-drop reference everywhere
    y_full = moe_reference(params, x, k=2)
    assert float(jnp.sum(y**2)) <= float(jnp.sum(y_full**2)) * 1.5


def test_moe_gradients_flow_through_router_and_experts(mesh):
    params = init_moe_params(jax.random.key(3), D, H, 8)
    params = place_moe_params(mesh, params)
    x = _tokens(32, seed=3)
    target = _tokens(32, seed=4)
    fn = moe_apply(mesh, k=2, capacity_factor=4.0)

    def loss(p):
        y, aux = fn(p, x)
        return jnp.mean((y - target) ** 2) + 0.01 * aux

    g = jax.grad(loss)(params)
    flat = jax.tree.leaves(g)
    assert all(np.isfinite(np.asarray(a)).all() for a in flat)
    # router must receive gradient (through the gate weights)
    assert float(jnp.max(jnp.abs(g.wg))) > 0
    # at least some experts trained
    assert float(jnp.max(jnp.abs(g.w1))) > 0

    # one SGD step reduces the loss
    l0 = float(loss(params))
    p1 = jax.tree.map(lambda p, gg: p - 0.1 * gg, params, g)
    assert float(loss(p1)) < l0


def test_moe_top1_router_gets_task_gradient(mesh):
    # Switch k=1 keeps the raw gate multiplier: normalizing would compute
    # g/g == 1 and cancel the router's task gradient exactly
    params = place_moe_params(mesh, init_moe_params(jax.random.key(8), D, H, 8))
    x = _tokens(32, seed=8)
    target = _tokens(32, seed=9)
    fn = moe_apply(mesh, k=1, capacity_factor=4.0)

    def task_loss(p):  # no aux term — gradient must come through the gate
        y, _ = fn(p, x)
        return jnp.mean((y - target) ** 2)

    g = jax.grad(task_loss)(params)
    assert float(jnp.max(jnp.abs(g.wg))) > 1e-5


def test_moe_aux_loss_sees_pre_drop_routing(mesh):
    # route everything to expert 0 by biasing the router: aux must report
    # the true imbalance (~E * 1 * P_0) even though capacity drops most
    # tokens — a post-drop f_e would collapse toward capacity/T
    params = init_moe_params(jax.random.key(6), D, H, 8)
    params = params._replace(
        wg=jnp.zeros_like(params.wg).at[:, 0].set(50.0)
    )
    x = jnp.abs(_tokens(64, seed=6)) + 0.5  # positive -> huge logit on e0
    fn = moe_apply(mesh, k=1, capacity_factor=0.25)
    _, aux = fn(place_moe_params(mesh, params), x)
    # fully collapsed top-1 routing: f_0 ~= 1, P_0 ~= 1 -> aux ~= E
    assert float(aux) > 4.0


def test_moe_rejects_multiple_experts_per_device(mesh):
    params = init_moe_params(jax.random.key(7), D, H, 16)  # 2 per device
    fn = moe_apply(mesh, k=2, capacity_factor=4.0)
    with pytest.raises(ValueError, match="one expert per device"):
        fn(place_moe_params(mesh, params), _tokens(32, seed=7))


def test_moe_deterministic(mesh):
    params = place_moe_params(mesh, init_moe_params(jax.random.key(5), D, H, 8))
    x = _tokens(40, seed=5)
    fn = moe_apply(mesh, k=2, capacity_factor=4.0)
    y1, a1 = fn(params, x)
    y2, a2 = fn(params, x)
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))
    assert float(a1) == float(a2)
