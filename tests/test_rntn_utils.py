"""RNTN + tree pipeline + utility tests."""

import numpy as np
import pytest

from deeplearning4j_tpu.models.rntn import RNTN, RNTNEval, topo_pack
from deeplearning4j_tpu.nlp.tree import (
    Tree,
    TreeVectorizer,
    binarize,
    collapse_unaries,
    parse_ptb,
    right_branching_tree,
)
from deeplearning4j_tpu.utils.counters import Counter, CounterMap
from deeplearning4j_tpu.utils.dedup import StringGrid, fingerprint
from deeplearning4j_tpu.utils.disk_queue import DiskBasedQueue
from deeplearning4j_tpu.utils import math_utils as mu
from deeplearning4j_tpu.utils.viterbi import Viterbi


def test_ptb_parse_roundtrip():
    s = "(3 (2 good) (1 (0 not) (2 bad)))"
    t = parse_ptb(s)
    assert t.label == "3"
    assert t.words() == ["good", "not", "bad"]
    assert str(t) == "(3 (2 good) (1 (0 not) (2 bad)))"


def test_binarize_and_collapse():
    t = parse_ptb("(S (A a) (B b) (C c) (D d))")
    b = binarize(t)
    for node in b.subtrees():
        assert len(node.children) <= 2
    assert b.words() == ["a", "b", "c", "d"]

    u = parse_ptb("(S (X (Y (A a))) (B b))")
    c = collapse_unaries(u)
    assert c.words() == ["a", "b"]
    assert c.depth() <= u.depth()


def test_right_branching_and_vectorizer():
    t = right_branching_tree(["a", "b", "c"])
    assert t.words() == ["a", "b", "c"]
    for node in t.subtrees():
        assert len(node.children) in (0, 2)
    trees = TreeVectorizer().trees("One two three. Four five.")
    assert len(trees) == 2


def test_topo_pack_children_before_parents():
    t = parse_ptb("(1 (0 a) (1 (0 b) (1 c)))")
    from deeplearning4j_tpu.nlp.vocab import VocabCache

    cache = VocabCache().fit([t.words()])
    word_ids, left, right, leaf, labels = topo_pack(t, cache, 2)
    n = len(word_ids)
    for i in range(n):
        if leaf[i] == 0:
            assert left[i] < i and right[i] < i


@pytest.mark.slow
def test_rntn_learns_sentiment():
    """Tiny sentiment task: label 1 trees contain 'good', label 0 'bad'."""
    rng = np.random.default_rng(0)
    pos_words = ["good", "great", "fine", "nice"]
    neg_words = ["bad", "awful", "poor", "sad"]
    fill = ["movie", "film", "plot", "was", "the"]
    trees = []
    for _ in range(60):
        pos = rng.random() < 0.5
        words = list(rng.choice(pos_words if pos else neg_words, 2)) + list(
            rng.choice(fill, 2)
        )
        rng.shuffle(words)
        t = binarize(right_branching_tree(words, label="1" if pos else "0"))
        for node in t.subtrees():
            node.label = t.label
        trees.append(t)
    model = RNTN(num_classes=2, dim=8, lr=0.1, seed=1, max_nodes=16)
    losses = model.fit_trees(trees, epochs=6)
    assert losses[-1] < losses[0]
    ev = RNTNEval()
    ev.eval(model, trees)
    assert ev.accuracy() > 0.85, ev.accuracy()


@pytest.mark.slow
def test_rntn_per_label_tables_on_treebank():
    """Untied per-production parameter tables (≙ RNTN.java:94-135
    MultiDimensionalMaps — the capability the reference declares but
    only runs in flat simplifiedModel mode): productions discovered
    from nlp/parser.py's bundled treebank, label-indexed W/V/Wc_bin/
    Wc_un exercised via gather, node-category classification learned
    to high accuracy."""
    import copy

    from deeplearning4j_tpu.models.rntn import _pack_full, basic_category
    from deeplearning4j_tpu.nlp.parser import bundled_treebank

    # the r5 treebank grew to 229 trees for parser coverage; this test's
    # subject is the per-production TABLE mechanics, for which the first
    # 40 trees already span the category variety — full-treebank
    # training tripled the slow lane's longest test for no extra signal
    trees = [binarize(t) for t in bundled_treebank()[:40]]
    cats = sorted(
        {basic_category(n.label, False) for t in trees for n in t.subtrees()}
    )
    cat_id = {c: i for i, c in enumerate(cats)}
    assert len(cats) >= 10  # NP/VP/PP/S + POS tags — real category variety

    def relabel(t):
        cat = basic_category(t.label, False)
        for c in t.children:
            relabel(c)
        t.label = str(cat_id[cat])

    relabeled = [copy.deepcopy(t) for t in trees]
    for t in relabeled:
        relabel(t)

    model = RNTN(
        num_classes=len(cats), dim=12, lr=0.1, seed=3, max_nodes=32,
        simplified_model=False, combine_classification=False, batch_size=10,
    )
    losses = model.fit_trees(relabeled, epochs=14)
    # the untied tables are real: one slot per discovered production
    assert len(model.prod_index) > 5
    assert model.params["W"].shape[0] == len(model.prod_index)
    assert model.params["Wc_un"].shape[0] == len(model.unary_index)
    assert losses[-1] < losses[0] / 10
    correct = total = 0
    for t in relabeled:
        gold = _pack_full(
            t, model.cache, model.num_classes, model.prod_index,
            model.unary_index, False,
        )["labels"]
        pred = model.predict_nodes(t)
        correct += int((pred == gold).sum())
        total += len(gold)
    assert correct / total > 0.9, correct / total


def test_viterbi_decodes_obvious_path():
    # two states; state 0 emits obs 0, state 1 emits obs 1
    trans = np.array([[0.8, 0.2], [0.2, 0.8]])
    emissions_for = lambda obs: np.array([[0.9, 0.1] if o == 0 else [0.1, 0.9] for o in obs])
    v = Viterbi(trans)
    path, score = v.decode(emissions_for([0, 0, 1, 1, 0]))
    assert path.tolist() == [0, 0, 1, 1, 0]
    assert score < 0


def test_counters():
    c = Counter(["a", "b", "a"])
    assert c.get_count("a") == 2
    assert c.arg_max() == "a"
    c.normalize()
    assert abs(c.total_count() - 1.0) < 1e-9

    cm = CounterMap()
    cm.increment_count("x", "y", 2.0)
    cm.increment_count("x", "z")
    assert cm.get_count("x", "y") == 2.0
    assert cm.get_counter("x").arg_max() == "y"


def test_math_utils():
    assert mu.entropy([0.5, 0.5]) == pytest.approx(1.0)
    assert mu.entropy([1.0]) == 0.0
    assert mu.log_sum_exp([0.0, 0.0]) == pytest.approx(np.log(2))
    assert mu.cosine_similarity([1, 0], [1, 0]) == pytest.approx(1.0)
    assert mu.correlation([1, 2, 3], [2, 4, 6]) == pytest.approx(1.0)
    assert mu.next_power_of_2(17) == 32
    assert mu.information_gain([0.5, 0.5], [(0.5, [1.0]), (0.5, [1.0])]) == pytest.approx(1.0)


def test_fingerprint_dedup():
    assert fingerprint("Héllo,  World!") == fingerprint("world hello")
    grid = StringGrid([["Tom Cruise", "1"], ["cruise, tom", "2"], ["Other", "3"]])
    clusters = grid.clusters_by_fingerprint(0)
    assert any(len(v) == 2 for v in clusters.values())
    assert len(grid.dedup_column(0).rows) == 2


def test_disk_queue(tmp_path):
    q = DiskBasedQueue(tmp_path / "q")
    assert q.is_empty()
    q.add({"a": 1})
    q.add([1, 2])
    assert len(q) == 2
    assert q.peek() == {"a": 1}
    assert q.poll() == {"a": 1}
    assert q.poll() == [1, 2]
    assert q.poll() is None
