import sys; sys.path.insert(0, "/root/repo")
import time
import jax, jax.numpy as jnp, numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
import deeplearning4j_tpu.ops.pallas_kernels as PK

B,H,T,D = 2,8,8192,64
bh=B*H
rng=np.random.default_rng(0)
QF,KF,VF,DO = (jnp.asarray(rng.normal(size=(bh,T,D)).astype(np.float32)).astype(jnp.bfloat16) for _ in range(4))
# realistic lse/delta: from the actual forward so p<=~1
out, LSE = PK._flash_fwd_call(QF,KF,VF,1024,1024,False,True)
DELTA = jnp.sum(DO.astype(jnp.float32)*out.astype(jnp.float32),axis=-1)[...,None]
log2e = 1.4426950408889634

def make_bwd(variant, BQ, BK):
    n_q=T//BQ; n_k=T//BK
    scale=1.0/(D**0.5)
    exp2 = "exp2" in variant
    bf16ds = "bf16ds" in variant
    def kernel(q_ref,k_ref,v_ref,do_ref,lse_ref,delta_ref,dq_ref,dk_ref,dv_ref,dk_s,dv_s):
        kk=pl.program_id(1); qq=pl.program_id(2)
        k_start=kk*BK; q_start=qq*BQ
        @pl.when(qq==0)
        def _i():
            dk_s[:]=jnp.zeros_like(dk_s); dv_s[:]=jnp.zeros_like(dv_s)
        def compute(masked):
            k_blk=k_ref[0]; v_blk=v_ref[0]
            qs = scale*log2e if exp2 else scale
            q=q_ref[0]*jnp.asarray(qs,q_ref.dtype)
            do_=do_ref[0]; l_=lse_ref[0,:,0]; dl=delta_ref[0,:,0]
            s=jnp.dot(q,k_blk.T,preferred_element_type=jnp.float32)
            if masked:
                s=s+PK._causal_bias(q_start,k_start,BQ,BK)
            p=jnp.exp2(s-l_[:,None]) if exp2 else jnp.exp(s-l_[:,None])
            dv_s[:]=dv_s[:]+jnp.dot(p.astype(do_.dtype).T,do_,preferred_element_type=jnp.float32)
            dp=jnp.dot(do_,v_blk.T,preferred_element_type=jnp.float32)
            if bf16ds:
                ds=p.astype(q_ref.dtype)*(dp-dl[:,None]).astype(q_ref.dtype)
            else:
                ds=(p*(dp-dl[:,None])).astype(q_ref.dtype)
            if exp2:
                # q was scaled by scale*log2e; dk must use scale only
                dk_s[:]=dk_s[:]+jnp.dot(ds.T,q,preferred_element_type=jnp.float32)*jnp.float32(1.0/log2e)
            else:
                dk_s[:]=dk_s[:]+jnp.dot(ds.T,q,preferred_element_type=jnp.float32)
            dq_c=jnp.dot(ds,k_blk,preferred_element_type=jnp.float32)*scale
            @pl.when(kk==0)
            def _a(): dq_ref[0]=dq_c
            @pl.when(kk!=0)
            def _b(): dq_ref[0]=dq_ref[0]+dq_c
        PK._causal_dispatch(compute,True,q_start,k_start,BQ,BK)
        @pl.when(qq==n_q-1)
        def _f():
            dk_ref[0]=dk_s[:].astype(dk_ref.dtype); dv_ref[0]=dv_s[:].astype(dv_ref.dtype)
    def call(q,k,v,do,lse,delta):
        return pl.pallas_call(kernel,
            out_shape=(jax.ShapeDtypeStruct((bh,T,D),jnp.float32),
                       jax.ShapeDtypeStruct((bh,T,D),k.dtype),
                       jax.ShapeDtypeStruct((bh,T,D),v.dtype)),
            grid=(bh,n_k,n_q),
            in_specs=[pl.BlockSpec((1,BQ,D),lambda i,j,qq:(i,qq,0)),
                      pl.BlockSpec((1,BK,D),lambda i,j,qq:(i,j,0)),
                      pl.BlockSpec((1,BK,D),lambda i,j,qq:(i,j,0)),
                      pl.BlockSpec((1,BQ,D),lambda i,j,qq:(i,qq,0)),
                      pl.BlockSpec((1,BQ,1),lambda i,j,qq:(i,qq,0)),
                      pl.BlockSpec((1,BQ,1),lambda i,j,qq:(i,qq,0))],
            out_specs=(pl.BlockSpec((1,BQ,D),lambda i,j,qq:(i,qq,0)),
                       pl.BlockSpec((1,BK,D),lambda i,j,qq:(i,j,0)),
                       pl.BlockSpec((1,BK,D),lambda i,j,qq:(i,j,0))),
            scratch_shapes=[pltpu.VMEM((BK,D),jnp.float32),pltpu.VMEM((BK,D),jnp.float32)],
            compiler_params=pltpu.CompilerParams(dimension_semantics=("parallel","arbitrary","arbitrary")),
            interpret=False)(q,k,v,do,lse,delta)
    return call

N_CHAIN = 12
def chained(variant, BQ, BK):
    call = make_bwd(variant, BQ, BK)
    exp2 = "exp2" in variant
    def f(q,k,v,do,lse,delta):
        lse2 = lse*log2e if exp2 else lse
        dqs = jnp.zeros((), jnp.float32)
        for i in range(N_CHAIN):
            dq,dk,dv = call(q,k,v,do,lse2,delta)
            # feed dq back into do (bf16) to serialize; prevents CSE
            do = dq.astype(do.dtype)*jnp.bfloat16(1e-3) + do*jnp.bfloat16(0.999)
            dqs = dqs + jnp.sum(dq[0,0].astype(jnp.float32))
        return dqs
    return jax.jit(f)

def timeit(f, reps=3, windows=3):
    x=f(QF,KF,VF,DO,LSE,DELTA); _=float(x)
    best=1e9
    for w in range(windows):
        t0=time.time()
        for _ in range(reps): x=f(QF,KF,VF,DO,LSE,DELTA)
        _=float(x)
        best=min(best,(time.time()-t0)/reps)
    return best/N_CHAIN*1000

if __name__=="__main__":
    import itertools
    cfgs = [("base",1024,1024),("exp2",1024,1024),("bf16ds",1024,1024),("exp2_bf16ds",1024,1024),
            ("base",512,1024),("base",1024,512),("base",512,2048),("base",1024,2048),("base",2048,1024)]
    for v,bq,bk in cfgs:
        try:
            ms = timeit(chained(v,bq,bk))
            print(f"{v:12s} {bq}/{bk}: {ms:.3f} ms/kernel")
        except Exception as e:
            print(f"{v:12s} {bq}/{bk}: FAIL {str(e)[:80]}")
