import sys; sys.path.insert(0, "/root/repo")
import time
import jax, jax.numpy as jnp, numpy as np
from deeplearning4j_tpu.ops.pallas_kernels import _flash_fwd_call

B,H,T,D = 2,8,8192,64
bh=B*H
rng=np.random.default_rng(0)
QF,KF,VF = (jnp.asarray(rng.normal(size=(bh,T,D)).astype(np.float32)).astype(jnp.bfloat16) for _ in range(3))
N=12
def chained(BQ,BK):
    def f(q,k,v):
        acc=jnp.zeros((),jnp.float32)
        for i in range(N):
            o,lse = _flash_fwd_call(q,k,v,BQ,BK,False,True)
            q = o*jnp.bfloat16(0.5)+q*jnp.bfloat16(0.5)
            acc = acc+jnp.sum(o[0,0].astype(jnp.float32))
        return acc
    return jax.jit(f)
def timeit(f,reps=3,windows=3):
    x=f(QF,KF,VF); _=float(x)
    best=1e9
    for w in range(windows):
        t0=time.time()
        for _ in range(reps): x=f(QF,KF,VF)
        _=float(x)
        best=min(best,(time.time()-t0)/reps)
    return best/N*1000
for bq,bk in [(1024,1024),(512,1024),(512,2048),(1024,512),(256,2048),(2048,512),(512,512)]:
    try:
        print(f"fwd {bq}/{bk}: {timeit(chained(bq,bk)):.3f} ms/kernel")
    except Exception as e:
        print(f"fwd {bq}/{bk}: FAIL {str(e)[:60]}")
