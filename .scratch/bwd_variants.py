import sys; sys.path.insert(0, "/root/repo")
import time
import jax, jax.numpy as jnp, numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
import deeplearning4j_tpu.ops.pallas_kernels as PK

B,H,T,D = 2,8,8192,64
BQ=BK=1024
bh=B*H
rng=np.random.default_rng(0)
qf,kf,vf,do = (jnp.asarray(rng.normal(size=(bh,T,D)).astype(np.float32)).astype(jnp.bfloat16) for _ in range(4))
lse = jnp.asarray(rng.normal(size=(bh,T,1)).astype(np.float32))
delta = jnp.asarray(rng.normal(size=(bh,T,1)).astype(np.float32))
log2e = 1.4426950408889634

def make_bwd(variant, BQ=BQ, BK=BK):
    n_q=T//BQ; n_k=T//BK
    scale=1.0/(D**0.5)
    def kernel(q_ref,k_ref,v_ref,do_ref,lse_ref,delta_ref,dq_ref,dk_ref,dv_ref,dk_s,dv_s):
        kk=pl.program_id(1); qq=pl.program_id(2)
        k_start=kk*BK; q_start=qq*BQ
        @pl.when(qq==0)
        def _i():
            dk_s[:]=jnp.zeros_like(dk_s); dv_s[:]=jnp.zeros_like(dv_s)
        def compute(masked):
            k_blk=k_ref[0]; v_blk=v_ref[0]
            q=q_ref[0]*jnp.asarray(scale,q_ref.dtype)
            do_=do_ref[0]; l_=lse_ref[0,:,0]; dl=delta_ref[0,:,0]
            s=jnp.dot(q,k_blk.T,preferred_element_type=jnp.float32)
            if masked:
                s=s+PK._causal_bias(q_start,k_start,BQ,BK)
            if variant=="noexp":
                p=(s-l_[:,None])*0.001
            elif variant in ("exp2","exp2bf16"):
                p=jnp.exp2(s*log2e-l_[:,None])  # lse pre-scaled by log2e outside
            else:
                p=jnp.exp(s-l_[:,None])
            dv_s[:]=dv_s[:]+jnp.dot(p.astype(do_.dtype).T,do_,preferred_element_type=jnp.float32)
            dp=jnp.dot(do_,v_blk.T,preferred_element_type=jnp.float32)
            if variant in ("bf16ds","exp2bf16"):
                ds=(p.astype(q.dtype)*(dp-dl[:,None]).astype(q.dtype))
            else:
                ds=(p*(dp-dl[:,None])).astype(q.dtype)
            dk_s[:]=dk_s[:]+jnp.dot(ds.T,q,preferred_element_type=jnp.float32)
            dq_c=jnp.dot(ds,k_blk,preferred_element_type=jnp.float32)*scale
            @pl.when(kk==0)
            def _a(): dq_ref[0]=dq_c
            @pl.when(kk!=0)
            def _b(): dq_ref[0]=dq_ref[0]+dq_c
        PK._causal_dispatch(compute,True,q_start,k_start,BQ,BK)
        @pl.when(qq==n_q-1)
        def _f():
            dk_ref[0]=dk_s[:].astype(dk_ref.dtype); dv_ref[0]=dv_s[:].astype(dv_ref.dtype)
    return pl.pallas_call(kernel,
        out_shape=(jax.ShapeDtypeStruct((bh,T,D),jnp.float32),
                   jax.ShapeDtypeStruct((bh,T,D),kf.dtype),
                   jax.ShapeDtypeStruct((bh,T,D),vf.dtype)),
        grid=(bh,n_k,n_q),
        in_specs=[pl.BlockSpec((1,BQ,D),lambda i,j,qq:(i,qq,0)),
                  pl.BlockSpec((1,BK,D),lambda i,j,qq:(i,j,0)),
                  pl.BlockSpec((1,BK,D),lambda i,j,qq:(i,j,0)),
                  pl.BlockSpec((1,BQ,D),lambda i,j,qq:(i,qq,0)),
                  pl.BlockSpec((1,BQ,1),lambda i,j,qq:(i,qq,0)),
                  pl.BlockSpec((1,BQ,1),lambda i,j,qq:(i,qq,0))],
        out_specs=(pl.BlockSpec((1,BQ,D),lambda i,j,qq:(i,qq,0)),
                   pl.BlockSpec((1,BK,D),lambda i,j,qq:(i,j,0)),
                   pl.BlockSpec((1,BK,D),lambda i,j,qq:(i,j,0))),
        scratch_shapes=[pltpu.VMEM((BK,D),jnp.float32),pltpu.VMEM((BK,D),jnp.float32)],
        compiler_params=pltpu.CompilerParams(dimension_semantics=("parallel","arbitrary","arbitrary")),
        interpret=False)

def timeit(fn,*a,reps=5):
    out=fn(*a); _=float(jnp.sum(out[0]))
    t0=time.time()
    for _ in range(reps): out=fn(*a)
    _=float(jnp.sum(out[0]))
    return (time.time()-t0)/reps*1000

if __name__ == "__main__":
    for variant in ["base","exp2","bf16ds","exp2bf16","noexp"]:
        f=jax.jit(make_bwd(variant))
        l2 = lse*log2e if variant in ("exp2","exp2bf16") else lse
        print(f"{variant}: {timeit(f,qf,kf,vf,do,l2,delta):.2f} ms bwd-only")

def trial_matrix():
    fns = {v: jax.jit(make_bwd(v)) for v in ["base","exp2","bf16ds","exp2bf16","noexp"]}
    args = {v: (qf,kf,vf,do, lse*log2e if v.startswith("exp2") else lse, delta) for v in fns}
    for v,f in fns.items(): timeit(f,*args[v],reps=2)  # warm all
    import collections
    res = collections.defaultdict(list)
    for t in range(4):
        for v,f in fns.items():
            res[v].append(timeit(f,*args[v],reps=10))
    for v in fns:
        r = res[v]
        print(f"{v}: min {min(r):.2f} ms  runs {[round(x,2) for x in r]}")
