#!/usr/bin/env python
"""Fleet observability smoke: router + controller + 2 demo replicas
over real HTTP.

Boots two ``serve --demo`` replica processes and one ``router`` process
(each exporting its tracer via --trace-out), drives generate requests
through the router, checks the live observability surfaces
(``/debug/dump`` flight bundle, per-family ``serve_program_seconds``
attribution on ``/metrics``). Then boots a ``controller`` over the SAME
replicas with disaggregated roles (replica 0 = prefill, replica 1 =
decode) and sends one long-prompt request through the transfer path —
prefill computes the KV segment and pushes it replica-to-replica to the
decode target, whose generate full-hits. Shuts the fleet down, stitches
the per-process trace exports with ``trace-merge``, and validates the
merged document structurally: >= 4 process tracks, every replica
admission span's ``parent_span_id`` resolving to a dispatch span on a
different track, cross-process flow arrows present, and the disagg
chain controller dispatch -> export prefill -> transfer -> kv_ingest
joined under ONE trace id.

CI runs this as the fleet lane; it is also a one-command local repro:

    JAX_PLATFORMS=cpu python scripts/fleet_smoke.py
"""

import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

BOOT_TIMEOUT_S = 240  # demo replicas compile their programs first

# every HTTP call in this smoke derives its socket timeout from one
# deadline budget and propagates the remainder downstream via
# X-Deadline-Ms, so a wedged fleet fails the lane in bounded time
# instead of hanging on an unbounded urlopen
GET_BUDGET_S = 30.0
GENERATE_BUDGET_S = 120.0


def _deadline_headers(budget_s):
    return {"X-Deadline-Ms": str(int(budget_s * 1000))}


def get(addr, path, budget_s=GET_BUDGET_S):
    url = f"http://{addr['host']}:{addr['port']}{path}"
    req = urllib.request.Request(url, headers=_deadline_headers(budget_s))
    with urllib.request.urlopen(req, timeout=budget_s) as r:
        return r.read()


def post_generate(addr, body, budget_s=GENERATE_BUDGET_S):
    req = urllib.request.Request(
        f"http://{addr['host']}:{addr['port']}/v1/generate",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json",
                 **_deadline_headers(budget_s)})
    with urllib.request.urlopen(req, timeout=budget_s) as r:
        return r.status, json.loads(r.read())


def wait_port_file(path, procs, timeout=BOOT_TIMEOUT_S):
    t0 = time.time()
    while time.time() - t0 < timeout:
        for p in procs:
            if p.poll() is not None:
                raise SystemExit(f"fleet process exited early: {p.args}")
        if os.path.exists(path):
            with open(path, encoding="utf-8") as f:
                return json.load(f)
        time.sleep(0.2)
    raise SystemExit(f"timed out waiting for {path}")


def prom_value(text, series):
    for line in text.splitlines():
        if line.startswith(series + " "):
            return float(line.split()[-1])
    raise SystemExit(f"{series} missing from /metrics")


def main():
    tmp = tempfile.mkdtemp(prefix="fleet-smoke-")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    procs = []
    traces = []
    try:
        port_files = []
        for i in range(2):
            pf = os.path.join(tmp, f"serve{i}.port")
            trace = os.path.join(tmp, f"serve{i}.trace.json")
            port_files.append(pf)
            traces.append(trace)
            procs.append(subprocess.Popen([
                sys.executable, "-m", "deeplearning4j_tpu", "serve",
                "--demo", "--port", "0", "--slots", "2",
                "--seq-len", "32", "--d-model", "32",
                "--n-layers", "2", "--n-heads", "4",
                "--port-file", pf, "--trace-out", trace,
                "--flight-dir", tmp,
                # replica 1 is the controller phase's decode target:
                # wire segments seat in its prefix cache
                *(["--prefix-cache"] if i == 1 else []),
            ], env=env))
        addrs = [wait_port_file(pf, procs) for pf in port_files]
        print(f"replicas up: {addrs}")

        rpf = os.path.join(tmp, "router.port")
        rtrace = os.path.join(tmp, "router.trace.json")
        traces.insert(0, rtrace)
        replica_flags = []
        for a in addrs:
            replica_flags += ["--replica", f"{a['host']}:{a['port']}"]
        procs.append(subprocess.Popen([
            sys.executable, "-m", "deeplearning4j_tpu", "router",
            *replica_flags, "--port", "0", "--port-file", rpf,
            "--trace-out", rtrace, "--flight-dir", tmp,
        ], env=env))
        raddr = wait_port_file(rpf, procs)
        print(f"router up: {raddr}")

        n_requests = 4
        for i in range(n_requests):
            status, body = post_generate(
                raddr, {"prompt": list(range(1, 8 + i)), "max_new": 3})
            assert status == 200 and body.get("tokens"), body
        print(f"{n_requests} requests routed OK")

        dump = json.loads(get(raddr, "/debug/dump"))
        assert any(e["kind"] == "dispatch" for e in dump["events"]), \
            "router flight recorder saw no dispatches"
        for a in addrs:
            rdump = json.loads(get(a, "/debug/dump"))
            assert rdump["reason"] == "debug_dump", rdump
        metrics = b"".join(get(a, "/metrics") for a in addrs).decode()
        assert "serve_program_seconds_total" in metrics, \
            "no per-family attribution on /metrics"
        assert "serve_mfu{" in metrics, "no serve_mfu gauges"
        print("debug dumps + attribution metrics OK")

        # -- disaggregated phase: controller over the same replicas --
        cpf = os.path.join(tmp, "controller.port")
        ctrace = os.path.join(tmp, "controller.trace.json")
        traces.insert(0, ctrace)
        procs.append(subprocess.Popen([
            sys.executable, "-m", "deeplearning4j_tpu", "controller",
            "--replica", f"{addrs[0]['host']}:{addrs[0]['port']}=prefill",
            "--replica", f"{addrs[1]['host']}:{addrs[1]['port']}=decode",
            "--disagg-threshold", "12", "--port", "0",
            "--port-file", cpf, "--trace-out", ctrace,
            "--flight-dir", tmp,
        ], env=env))
        caddr = wait_port_file(cpf, procs)
        print(f"controller up: {caddr}")

        # 16 tokens >= threshold: prefill computes KV on replica 0,
        # pushes the segment to replica 1, the generate full-hits there
        status, body = post_generate(
            caddr, {"prompt": list(range(1, 17)), "max_new": 3})
        assert status == 200 and body.get("tokens"), body
        pmx = get(addrs[0], "/metrics").decode()
        assert prom_value(
            pmx, 'serve_transfers_total{result="ok"}') >= 1, \
            "prefill replica recorded no successful transfer"
        assert prom_value(pmx, "serve_transfer_bytes_total") > 0
        dmx = get(addrs[1], "/metrics").decode()
        assert prom_value(
            dmx, 'serve_kv_ingests_total{result="stored"}') >= 1, \
            "decode replica seated no wire segment"
        print("disagg transfer path OK (segment pushed + seated)")
    finally:
        # SIGINT = the CLI's clean path: drain, then export --trace-out
        for p in reversed(procs):
            if p.poll() is None:
                p.send_signal(signal.SIGINT)
        for p in procs:
            try:
                p.wait(timeout=60)
            except subprocess.TimeoutExpired:
                p.kill()
    assert all(p.returncode == 0 for p in procs), \
        [(p.args[-1], p.returncode) for p in procs]

    merged_path = os.path.join(tmp, "merged.trace.json")
    subprocess.run(
        [sys.executable, "-m", "deeplearning4j_tpu", "trace-merge",
         *traces, "-o", merged_path],
        check=True, env=env)
    with open(merged_path, encoding="utf-8") as f:
        doc = json.load(f)
    evs = doc["traceEvents"]
    pids = {e["pid"] for e in evs}
    assert len(pids) >= 4, f"expected >= 4 process tracks, got {pids}"
    dispatches = {
        e["args"]["span_id"]: e for e in evs
        if e.get("ph") == "X" and e["name"] == "dispatch"
        and "span_id" in e.get("args", {})
    }
    admissions = [
        e for e in evs
        if e.get("ph") == "X" and e["name"] == "prefill"
        and e.get("args", {}).get("parent_span_id")
    ]
    assert len(admissions) >= n_requests, \
        f"only {len(admissions)} admission spans joined the fleet trace"
    for adm in admissions:
        parent = dispatches.get(adm["args"]["parent_span_id"])
        assert parent is not None, f"unresolved parent: {adm}"
        assert parent["pid"] != adm["pid"], "parent link not cross-process"
        assert parent["args"]["trace_id"] == adm["args"]["trace_id"]
    n_flows = sum(1 for e in evs if e.get("ph") == "s")
    assert n_flows >= n_requests, f"only {n_flows} flow arrows"

    # the disagg chain: controller dispatch -> export prefill ->
    # transfer -> kv_ingest, one trace id end to end, each hop on a
    # different process track
    by_span = {e["args"]["span_id"]: e for e in evs
               if e.get("ph") == "X" and "span_id" in e.get("args", {})}
    transfers = [e for e in evs
                 if e.get("ph") == "X" and e["name"] == "transfer"]
    assert transfers, "no transfer span in the merged trace"
    tr = transfers[0]
    exp = by_span[tr["args"]["parent_span_id"]]
    assert exp["name"] == "prefill" and \
        exp["args"].get("prefix") == "export", exp
    ing = next(e for e in evs
               if e.get("ph") == "X" and e["name"] == "kv_ingest")
    assert ing["args"]["parent_span_id"] == tr["args"]["span_id"]
    tid = tr["args"]["trace_id"]
    assert exp["args"]["trace_id"] == ing["args"]["trace_id"] == tid
    root = by_span[exp["args"]["parent_span_id"]]
    assert root["name"] == "dispatch" and \
        root["args"].get("leg") == "prefill", root
    assert len({root["pid"], exp["pid"], ing["pid"]}) == 3, \
        "disagg chain does not cross three processes"
    print(f"merged trace OK: {len(pids)} tracks, "
          f"{len(admissions)} admission spans all parented to "
          f"dispatches, {n_flows} flow arrows, disagg chain "
          f"controller->prefill->transfer->ingest under trace {tid} "
          f"-> {merged_path}")


if __name__ == "__main__":
    main()
