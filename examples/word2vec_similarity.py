"""Word2Vec skip-gram on a toy corpus, similarity + nearest words.

≙ Word2VecTests (reference: deeplearning4j-scaleout/deeplearning4j-nlp/
src/test/java/org/deeplearning4j/models/word2vec/Word2VecTests.java):
train on sentences, then query similarity("day", "night") and
wordsNearest.

Run: python examples/word2vec_similarity.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root

from deeplearning4j_tpu.models.word2vec import Word2Vec
from deeplearning4j_tpu.nlp.sentence_iterator import CollectionSentenceIterator

CORPUS = [
    "the day was bright and the night was dark",
    "day follows night and night follows day",
    "a bright day a dark night",
    "the sun rules the day the moon rules the night",
    "night and day are opposites",
    "every day has a night and every night has a day",
] * 50


def main():
    w2v = Word2Vec(layer_size=32, window=3, min_word_frequency=1, seed=7,
                   epochs=15)
    sents = CollectionSentenceIterator(CORPUS)
    w2v.build_vocab(sents)
    sents.reset()
    w2v.fit(sents)

    print("similarity(day, night) =", w2v.similarity("day", "night"))
    print("nearest to 'day':", w2v.words_nearest("day", top=5))


if __name__ == "__main__":
    main()
