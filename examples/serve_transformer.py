"""Continuous-batching serving engine under a synthetic request trace.

A fixed-shape batch of KV-cache slots decodes every active request in
one fused jitted step; between steps the host retires finished slots
and admits queued requests by prefilling their prompt into the freed
slot. Requests arrive on a deterministic pseudo-Poisson trace, overlap
in flight, and each still gets exactly the stream it would get decoding
alone (greedy parity is pinned by tests/test_serving.py).

Run (any host; CPU works):
  python examples/serve_transformer.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root

import jax
import numpy as np

from deeplearning4j_tpu.models.transformer import (
    TransformerConfig,
    init_transformer,
)
from deeplearning4j_tpu.serving import (
    Request,
    RequestScheduler,
    ServingEngine,
    run_request_trace,
)

PROMPTS = [
    b"the quick brown fox ",
    b"pack my box with ",
    b"five dozen liquor ",
    b"jumps over the lazy ",
    b"sphinx of black quartz ",
    b"judge my vow ",
    b"how vexingly quick ",
    b"daft zebras jump ",
]


def main():
    # Byte-level model, randomly initialized — the point here is the
    # serving machinery, not the prose. Swap in restored checkpoint
    # params for real output (see `python -m deeplearning4j_tpu serve`).
    cfg = TransformerConfig(
        vocab_size=256, d_model=64, n_heads=4, n_layers=2, d_ff=256,
        max_len=128,
    )
    params = init_transformer(jax.random.key(0), cfg)

    engine = ServingEngine(
        cfg, params, n_slots=4, temperature=0.8, top_k=20,
        decode_horizon=4,  # 4 fused decode steps per dispatched program
        scheduler=RequestScheduler(max_queue_depth=32),
    )

    # Deterministic pseudo-Poisson arrivals: 12 requests, mean 20ms
    # apart, over 4 slots — forces queueing, interleaving and slot reuse.
    rng = np.random.default_rng(0)
    offsets = np.cumsum(rng.exponential(0.020, 12))
    reqs = [
        Request(
            prompt=np.frombuffer(PROMPTS[i % len(PROMPTS)], np.uint8)
            .astype(np.int32),
            max_new=int(rng.integers(16, 48)),
        )
        for i in range(12)
    ]
    results = run_request_trace(engine, list(zip(offsets, reqs)))

    for r in reqs:
        text = bytes(int(t) % 256 for t in results[r.id]).decode(
            "latin-1", errors="replace"
        )
        print(f"{r.id} ({len(results[r.id])} toks): {text!r}")

    s = engine.metrics.summary()
    print(
        f"\n{s['n_finished']} requests, {s['n_generated']} tokens in "
        f"{s['steps']} fused steps | occupancy mean "
        f"{s['occupancy_mean']:.2f}/{engine.n_slots} slots | "
        f"TTFT p50 {s['ttft_p50_s'] * 1e3:.1f}ms p99 "
        f"{s['ttft_p99_s'] * 1e3:.1f}ms | TPOT p50 "
        f"{s['tpot_p50_s'] * 1e3:.2f}ms"
    )


if __name__ == "__main__":
    main()
