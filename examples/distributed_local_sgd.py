"""Distributed training two ways: per-step AllReduce DP and local-SGD
parameter averaging, on an 8-virtual-device mesh.

≙ the reference's two scaleout policies (SURVEY §2): IterativeReduce
per-round gradient aggregation (IterativeReduceWorkRouter + actor
round-trip) and Spark/YARN parameter averaging after k local fits
(SparkDl4jMultiLayer.java:144-148, yarn Master.compute:47-62) — both
re-expressed as single compiled SPMD programs whose collectives ride the
mesh instead of actor messages.

Runs on CPU with 8 virtual devices so it works anywhere; on a real TPU
slice the same code runs unchanged over the physical mesh. For REAL
multi-process distribution (2+ hosts over jax.distributed, discovery via
the network registry), see tests/distributed_worker.py and
tests/test_distributed_multiprocess.py.

Run: python examples/distributed_local_sgd.py
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root

os.environ["JAX_PLATFORMS"] = "cpu"  # demo: virtual devices; on a real
# TPU slice with >=8 chips, delete this line and the flags below
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
try:  # private API: absent/renamed on newer jax is fine — with
    # jax_platforms=cpu the axon factory is merely unused
    from jax._src import xla_bridge as _xb  # noqa: E402

    _xb._backend_factories.pop("axon", None)
except (ImportError, AttributeError):
    pass
import jax.numpy as jnp
import numpy as np
import optax

from deeplearning4j_tpu.datasets import fetchers
from deeplearning4j_tpu.parallel import DataParallelTrainer, local_sgd_step
from deeplearning4j_tpu.parallel import mesh as mesh_lib


def build_model():
    w_rng = np.random.default_rng(1)
    params = {
        "w1": jnp.asarray(w_rng.normal(size=(4, 16)).astype(np.float32) * 0.4),
        "b1": jnp.zeros((16,)),
        "w2": jnp.asarray(w_rng.normal(size=(16, 3)).astype(np.float32) * 0.4),
        "b2": jnp.zeros((3,)),
    }

    def loss_fn(p, xb, yb, key=None):
        h = jnp.tanh(xb @ p["w1"] + p["b1"])
        return optax.softmax_cross_entropy(h @ p["w2"] + p["b2"], yb).mean()

    return params, loss_fn


def main():
    ds = fetchers.iris().normalize_zero_mean_unit_variance()
    n = (len(ds.features) // 8) * 8
    x = jnp.asarray(ds.features[:n])
    y = jnp.asarray(ds.labels[:n])
    mesh = mesh_lib.data_parallel_mesh(8)
    print(f"mesh: {mesh.shape} over {len(jax.devices())} devices")

    # -- mode 1: per-step gradient AllReduce ------------------------------
    params, loss_fn = build_model()
    trainer = DataParallelTrainer(loss_fn, mesh=mesh, optimizer=optax.sgd(0.1))
    state = trainer.init(params)
    xs, ys = trainer.shard_global_batch(x, y)
    state, losses = trainer.run_steps(state, xs, ys, jax.random.key(0), 200)
    print(f"DP AllReduce: loss {float(losses[0]):.4f} -> "
          f"{float(losses[-1]):.4f}")

    # -- mode 2: local SGD + parameter averaging on a CNN -----------------
    # ≙ the north-star "Spark parameter-averaging distributed CNN"
    # config: each of the 8 devices runs k local steps of LeNet on its
    # shard, then parameters are pmean'd — one shard_map program per
    # round, no actor round-trips
    from deeplearning4j_tpu.models.lenet import build_lenet, lenet_loss

    net, cnn_params = build_lenet(seed=0)
    ds2 = fetchers.mnist(n=64)
    cx = jnp.asarray(ds2.features)
    cy = jnp.asarray(ds2.labels)
    step = local_sgd_step(lenet_loss(net), mesh, local_steps=4, lr=0.05)
    loss = None
    for i in range(25):  # 25 rounds x 4 local steps
        cnn_params, loss = step(cnn_params, cx, cy, jax.random.key(i))
    print(f"local-SGD CNN (k=4 averaging rounds): final loss "
          f"{float(loss):.4f}")


if __name__ == "__main__":
    main()
