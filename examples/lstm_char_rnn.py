"""Character-level LSTM language model — train and sample.

≙ the reference's char-RNN LSTM (models/classifiers/lstm/LSTM.java:36;
sequence training via BPTT, decoding :219 and BeamSearch :241): one-hot
characters in, next-character prediction out, trained with autodiff BPTT
(the jitted-scan re-expression of the reference's serial timestep loop),
then sampled greedily and with beam search.

Run: python examples/lstm_char_rnn.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn import conf as C
from deeplearning4j_tpu.nn.layers import get as get_layer

TEXT = (
    "the quick brown fox jumps over the lazy dog. "
    "pack my box with five dozen liquor jugs. "
) * 8


def main():
    chars = sorted(set(TEXT))
    v = len(chars)
    idx = {c: i for i, c in enumerate(chars)}
    seq = np.asarray([idx[c] for c in TEXT], np.int32)

    mod = get_layer("lstm")
    cfg = C.LayerConfig(layer_type="lstm", n_in=v, n_out=v, activation="tanh")
    params = mod.init(jax.random.key(0), cfg)

    # batch of overlapping windows, next-char targets
    t = 48
    starts = np.arange(0, len(seq) - t - 1, t // 2)
    xs = jax.nn.one_hot(
        jnp.asarray([seq[s : s + t] for s in starts]), v
    )
    ys = jax.nn.one_hot(
        jnp.asarray([seq[s + 1 : s + t + 1] for s in starts]), v
    )

    @jax.jit
    def step(p, lr):
        loss, g = jax.value_and_grad(
            lambda q: mod.supervised_score(q, cfg, xs, ys)
        )(p)
        return jax.tree.map(lambda pi, gi: pi - lr * gi, p, g), loss

    loss = None
    for i in range(600):
        params, loss = step(params, jnp.float32(1.0 if i < 400 else 0.3))
        if (i + 1) % 100 == 0:
            print(f"step {i + 1}: loss {float(loss):.4f}")

    # greedy sampling from the trained model (≙ LSTM.java:219)
    emb = jnp.eye(v)
    h = c = jnp.zeros((cfg.n_out,))
    ch = idx["t"]
    out = ["t"]
    for _ in range(60):
        logits, h, c = mod.tick(params, cfg, emb[ch], h, c)
        ch = int(jnp.argmax(logits))
        out.append(chars[ch])
    print("greedy sample:", "".join(out))

    # beam-search decode (≙ BeamSearch, LSTM.java:241-336)
    beams = mod.beam_search(
        params, cfg, emb[idx["p"]], emb, beam_size=3, n_steps=24
    )
    best, logp = beams[0]
    print("beam sample:  p" + "".join(chars[i] for i in best),
          f"(logp {logp:.2f})")


if __name__ == "__main__":
    main()
