"""Composed-parallelism transformer char-LM with sampled generation.

Beyond the reference (its only sequence model is the serial LSTM): a
byte-level decoder trained over a (data, model) mesh — Megatron tensor
parallelism via pjit shardings, optional MoE experts and FSDP — then
KV-cached sampling.

Run (any host; uses however many devices jax exposes):
  python examples/transformer_char_lm.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.models.transformer import (
    TransformerConfig,
    transformer_generate,
    transformer_train_step,
)
from deeplearning4j_tpu.parallel.mesh import dp_mp_mesh

CORPUS = (
    b"the quick brown fox jumps over the lazy dog. "
    b"pack my box with five dozen liquor jugs. "
) * 200


def main():
    n = len(jax.devices())
    tp = 2 if n % 2 == 0 and n > 1 else 1
    mesh = dp_mp_mesh(max(1, n // tp), tp)
    cfg = TransformerConfig(
        vocab_size=256, d_model=64, n_heads=4, n_layers=2, d_ff=256,
        max_len=129,
    )
    step, init_state, shard_tokens = transformer_train_step(mesh, cfg)
    params, opt_state = init_state(jax.random.key(0))

    arr = np.frombuffer(CORPUS, np.uint8).astype(np.int32)
    rng = np.random.default_rng(0)
    for i in range(200):
        starts = rng.integers(0, len(arr) - 129, 16)
        toks = np.stack([arr[s : s + 129] for s in starts])
        params, opt_state, loss = step(
            params, opt_state, shard_tokens(jnp.asarray(toks))
        )
        if (i + 1) % 50 == 0:
            print(f"step {i + 1}: loss {float(loss):.3f}")

    gen = transformer_generate(cfg)
    out = gen(params, jnp.asarray(arr[None, :16]), jax.random.key(1), 64,
              temperature=0.8, top_k=20)
    print("sample:", bytes(np.asarray(out[0], np.uint8).tolist()).decode("latin-1"))

    # int8 serving: weight-only quantization (per-channel scales, dequant
    # fused into the matmul reads) over the float KV cache — the winning
    # production composite on TPU (PERF.md r5 crossover analysis)
    from deeplearning4j_tpu.models.transformer import quantize_decode_params

    qparams = quantize_decode_params(params, cfg)
    out_q = gen(qparams, jnp.asarray(arr[None, :16]), jax.random.key(1), 64,
                temperature=0.8, top_k=20)
    print(
        "int8 sample:",
        bytes(np.asarray(out_q[0], np.uint8).tolist()).decode("latin-1"),
    )


if __name__ == "__main__":
    main()
