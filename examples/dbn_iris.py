"""DBN on Iris — the reference's de-facto acceptance test, end to end.

≙ MultiLayerTest.testDbn (reference:
deeplearning4j-core/src/test/java/org/deeplearning4j/nn/multilayer/
MultiLayerTest.java:79-116): stacked Gaussian-visible RBMs pretrained
with CD-1, conjugate-gradient finetune, evaluated with the confusion
matrix / F1 machinery.

Run: python examples/dbn_iris.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root

from deeplearning4j_tpu.datasets import ListDataSetIterator, fetchers
from deeplearning4j_tpu.evaluation import Evaluation
from deeplearning4j_tpu.models import MultiLayerNetwork
from deeplearning4j_tpu.nn import conf as C


def main():
    ds = fetchers.iris().normalize_zero_mean_unit_variance()
    train, test = ds.split_test_and_train(110)

    base = C.LayerConfig(
        layer_type="rbm",
        activation="tanh",
        visible_unit=C.VisibleUnit.GAUSSIAN,
        hidden_unit=C.HiddenUnit.BINARY,
        lr=0.05,
        k=1,
        num_iterations=100,
        optimization_algo=C.OptimizationAlgorithm.CONJUGATE_GRADIENT,
    )
    mc = C.list_builder(
        base, sizes=[6, 4], n_in=4, n_out=3, hidden_layer_type="rbm"
    )
    mc.backward = True

    net = MultiLayerNetwork(mc)
    net.init()
    net.fit(ListDataSetIterator(train, 110))

    ev = Evaluation(3)
    ev.eval(test.labels, net.output(test.features))
    print(ev.stats())


if __name__ == "__main__":
    main()
