"""Generic record readers -> batched DataSets -> supervised training.

≙ the reference's Canova bridge demo (RecordReaderDataSetIterator over a
CSV record reader feeding MultiLayerNetwork.fit). Runs offline: writes a
small CSV, streams it through the reader bridge, fits an MLP, and
reports held-out accuracy. Also shows the SVMLight reader and the
per-category word2vec analogy report surface.

Run: python examples/record_reader_training.py
"""

import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root

import numpy as np  # noqa: E402

from deeplearning4j_tpu.datasets.records import (  # noqa: E402
    CSVRecordReader,
    RecordReaderDataSetIterator,
    SVMLightRecordReader,
)
from deeplearning4j_tpu.models.multilayer import MultiLayerNetwork  # noqa: E402
from deeplearning4j_tpu.nn import conf as C  # noqa: E402


def main() -> None:
    rng = np.random.default_rng(0)
    n = 400
    x = rng.normal(size=(n, 4)).astype(np.float32)
    labels = (x[:, 0] + x[:, 1] > 0).astype(int)
    x[:, 2] += labels * 1.5  # make the label recoverable

    workdir = Path(tempfile.mkdtemp())
    csv = workdir / "train.csv"
    with open(csv, "w") as f:
        f.write("f1,f2,f3,f4,label\n")
        for row, lab in zip(x[:320], labels[:320]):
            f.write(",".join(f"{v:.5f}" for v in row) + f",{lab}\n")

    it = RecordReaderDataSetIterator(
        CSVRecordReader(csv, skip_lines=1), batch_size=64,
        label_index=-1, num_classes=2,
    )
    cfg = C.list_builder(
        C.LayerConfig(layer_type="dense", activation="tanh",
                      num_iterations=40),
        sizes=[16], n_in=4, n_out=2, pretrain=False,
    )
    net = MultiLayerNetwork(cfg, seed=0)
    net.fit(it)
    acc = float((net.predict(x[320:]) == labels[320:]).mean())
    print(f"CSV records -> MLP held-out accuracy: {acc:.3f}")

    # the same pipeline over LibSVM sparse text (label -1 maps to class 0)
    svm = workdir / "train.svm"
    with open(svm, "w") as f:
        for row, lab in zip(x[:64], labels[:64]):
            feats = " ".join(f"{j + 1}:{v:.4f}" for j, v in enumerate(row))
            f.write(f"{1 if lab else -1} {feats}\n")
    batch = next(iter(RecordReaderDataSetIterator(
        SVMLightRecordReader(svm, n_features=4), batch_size=64,
        label_index=-1, num_classes=2,
    )))
    print(f"SVMLight batch: features {batch.features.shape}, "
          f"labels {batch.labels.shape}")


if __name__ == "__main__":
    main()
